"""Multi-host serving control plane: join, shard assignment, health
gossip, failure detection, elastic regeneration.

SURVEY §7 stage 8's host-coordination layer. On TPU pods the *data
plane* is a single SPMD program — XLA collectives over ICI move the
tensors, and ``jax.distributed`` launches every host into one runtime
(see :func:`ShardAssignment.jax_initialize_args`). What that runtime
does NOT provide is the service-level lifecycle around it: who is in
the serving group, which process is which rank, how a dead host is
detected, and how survivors agree to relaunch. The reference's analog
is its service client + gRPC control plane
(/root/reference/pkg/gofr/service/new.go:68, grpc.go:89); this module
plays that role with the framework's own building blocks — the leader
is a set of HTTP routes on an :class:`~gofr_tpu.app.App`, workers dial
it through :func:`~gofr_tpu.service.new_http_service` (circuit
breaker + retry included).

Protocol (all JSON over the framework's HTTP):

- ``POST /control/join`` {host_id, address, n_devices, health?}
  -> {generation, assignment} and bumps the generation: membership
  changed, every host must re-coordinate.
- ``POST /control/heartbeat`` {host_id, generation, health?}
  -> {ok, generation, assignment} — a worker heartbeating with a stale
  generation learns its new assignment right there (elastic restart:
  ranks are contiguous again after an eviction or a join).
- ``GET /control/topology`` -> members, assignments, gossiped health —
  also surfaced through the leader app's health endpoint.

Failure detection: the leader sweeps heartbeat deadlines; a host that
misses ``eviction_misses`` intervals is evicted and the generation
bumps. Workers detect leader loss through the service client's circuit
breaker and keep retrying with backoff.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ..http.errors import ErrorInvalidParam, HTTPError


class StaleGeneration(HTTPError):
    """The leader no longer knows this host: a 409 telling the worker
    to rejoin (which returns the fresh assignment)."""

    status_code = 409


@dataclass
class ShardAssignment:
    """One host's slice of the serving group."""

    host_id: str
    rank: int
    world_size: int
    n_devices: int
    generation: int
    coordinator: str  # host:port every jax.distributed process dials

    def jax_initialize_args(self) -> dict[str, Any]:
        """kwargs for ``jax.distributed.initialize`` — the hand-off
        point from control plane to SPMD data plane."""
        return {"coordinator_address": self.coordinator,
                "num_processes": self.world_size,
                "process_id": self.rank}

    def to_dict(self) -> dict[str, Any]:
        return {"host_id": self.host_id, "rank": self.rank,
                "world_size": self.world_size,
                "n_devices": self.n_devices,
                "generation": self.generation,
                "coordinator": self.coordinator}


@dataclass
class _Member:
    host_id: str
    address: str
    n_devices: int
    last_seen: float
    health: dict = field(default_factory=dict)


class ControlPlaneLeader:
    """Leader state + the routes that expose it. Attach to any App:

    >>> leader = ControlPlaneLeader(coordinator="10.0.0.1:8476")
    >>> leader.install(app)        # POST /control/join, /control/heartbeat
    """

    def __init__(self, *, coordinator: str = "",
                 heartbeat_interval_s: float = 2.0,
                 eviction_misses: int = 3,
                 logger: Any = None) -> None:
        self.coordinator = coordinator
        self.heartbeat_interval_s = heartbeat_interval_s
        self.eviction_misses = eviction_misses
        self.logger = logger
        self.generation = 0
        self._members: dict[str, _Member] = {}
        self._lock = threading.Lock()
        self._sweeper: threading.Thread | None = None
        self._running = False

    # ------------------------------------------------------------ state
    def _ranks_locked(self) -> dict[str, int]:
        """THE rank mapping: deterministic contiguous ranks sorted by
        host_id, so every caller computes the same view for a given
        membership. Both assignments and topology derive from here."""
        return {h: i for i, h in enumerate(sorted(self._members))}

    def _assignment_locked(self, host_id: str) -> ShardAssignment:
        ranks = self._ranks_locked()
        return ShardAssignment(
            host_id=host_id, rank=ranks[host_id],
            world_size=len(ranks),
            n_devices=self._members[host_id].n_devices,
            generation=self.generation, coordinator=self.coordinator)

    def join(self, host_id: str, address: str, n_devices: int,
             health: dict | None = None) -> ShardAssignment:
        if not host_id:
            raise ErrorInvalidParam("host_id")
        with self._lock:
            self.generation += 1  # membership changed for everyone
            self._members[host_id] = _Member(
                host_id=host_id, address=address,
                n_devices=max(1, int(n_devices)),
                last_seen=time.time(), health=dict(health or {}))
            assignment = self._assignment_locked(host_id)
        if self.logger:
            self.logger.info(
                "host joined serving group", host=host_id,
                rank=assignment.rank, world=assignment.world_size,
                generation=self.generation)
        return assignment

    def heartbeat(self, host_id: str, generation: int,
                  health: dict | None = None
                  ) -> tuple[ShardAssignment, bool]:
        """-> (assignment, changed): ``changed`` is True when the
        worker's view was stale — its signal to re-coordinate."""
        with self._lock:
            member = self._members.get(host_id)
            if member is None:
                raise StaleGeneration("unknown host: rejoin required")
            member.last_seen = time.time()
            if health is not None:
                member.health = dict(health)
            return (self._assignment_locked(host_id),
                    generation != self.generation)

    def evict(self, host_id: str) -> None:
        with self._lock:
            if self._members.pop(host_id, None) is None:
                return
            self.generation += 1
        if self.logger:
            self.logger.warn("host evicted from serving group",
                             host=host_id, generation=self.generation)

    def topology(self) -> dict[str, Any]:
        with self._lock:
            ranks = self._ranks_locked()
            return {
                "generation": self.generation,
                "world_size": len(self._members),
                "members": {
                    m.host_id: {"address": m.address,
                                "n_devices": m.n_devices,
                                "rank": ranks[m.host_id],
                                "last_seen": m.last_seen,
                                "health": m.health}
                    for m in self._members.values()},
            }

    def health_check(self) -> dict[str, Any]:
        topo = self.topology()
        degraded = [h for h, m in topo["members"].items()
                    if m["health"].get("status") not in (None, "UP")]
        status = "UP" if not degraded else "DEGRADED"
        return {"status": status,
                "details": {"generation": topo["generation"],
                            "world_size": topo["world_size"],
                            "degraded_hosts": degraded}}

    # ---------------------------------------------------------- sweeper
    def _sweep_once(self) -> None:
        deadline = time.time() - (self.heartbeat_interval_s
                                  * self.eviction_misses)
        with self._lock:  # joins mutate _members concurrently
            dead = [h for h, m in self._members.items()
                    if m.last_seen < deadline]
        for host_id in dead:
            self.evict(host_id)

    def start(self) -> None:
        self._running = True

        def run() -> None:
            while self._running:
                self._sweep_once()
                time.sleep(self.heartbeat_interval_s / 2)

        self._sweeper = threading.Thread(target=run, daemon=True,
                                         name="control-plane-sweeper")
        self._sweeper.start()

    def stop(self) -> None:
        self._running = False

    # ------------------------------------------------------------ routes
    def install(self, app: Any) -> None:
        """Register the control routes and start the sweeper when the
        app starts (reference startup-hook pattern, gofr.go:359)."""

        @app.post("/control/join")
        def join(ctx):
            body = ctx.bind() or {}
            assignment = self.join(
                str(body.get("host_id", "")),
                str(body.get("address", "")),
                int(body.get("n_devices", 1)),
                body.get("health"))
            # the assignment's generation, not a re-read of
            # self.generation: a concurrent join may have bumped it
            return {"generation": assignment.generation,
                    "assignment": assignment.to_dict()}

        @app.post("/control/heartbeat")
        def heartbeat(ctx):
            body = ctx.bind() or {}
            assignment, changed = self.heartbeat(
                str(body.get("host_id", "")),
                int(body.get("generation", -1)),
                body.get("health"))
            return {"ok": True, "changed": changed,
                    "generation": assignment.generation,
                    "assignment": assignment.to_dict()}

        @app.get("/control/topology")
        def topology(ctx):
            return self.topology()

        app.container.register_health_check("control_plane", self)

        @app.on_start
        def _start_sweeper():
            self.start()

        app.on_shutdown(self.stop)


class WorkerAgent:
    """A serving host's side of the protocol: join once, heartbeat on a
    thread, and invoke ``on_assignment`` every time the generation
    changes — the hook where the host tears down and relaunches its
    SPMD program with the new rank/world (elastic restart)."""

    def __init__(self, leader_url: str, *, host_id: str,
                 address: str = "", n_devices: int = 1,
                 heartbeat_interval_s: float = 2.0,
                 on_assignment: Callable[[ShardAssignment], None]
                 | None = None,
                 health_source: Callable[[], dict] | None = None,
                 logger: Any = None, service: Any = None) -> None:
        from ..service import CircuitBreaker, Retry, new_http_service
        self.host_id = host_id
        self.address = address
        self.n_devices = n_devices
        self.heartbeat_interval_s = heartbeat_interval_s
        self.on_assignment = on_assignment
        self.health_source = health_source or (lambda: {"status": "UP"})
        self.logger = logger
        self._service = service if service is not None else \
            new_http_service(leader_url, Retry(max_retries=2),
                             CircuitBreaker(threshold=5, interval_s=2.0),
                             logger=logger)
        self.assignment: ShardAssignment | None = None
        self._running = False
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------- wire
    def _post(self, path: str, body: dict) -> dict:
        import asyncio
        # the heartbeat thread is sync; the service client (circuit
        # breaker, retry, tracing) is async — one loop per call is
        # cheap at heartbeat cadence
        response = asyncio.run(self._service.post(path, json=body))
        if response.status == 409:
            return {"rejoin": True}
        if response.status >= 400:
            raise RuntimeError(
                f"control plane {path} -> {response.status}")
        data = response.json()
        return data.get("data", data)

    def _apply(self, payload: dict) -> None:
        raw = payload.get("assignment")
        if raw is None:
            return
        new = ShardAssignment(
            host_id=raw["host_id"], rank=int(raw["rank"]),
            world_size=int(raw["world_size"]),
            n_devices=int(raw["n_devices"]),
            generation=int(raw["generation"]),
            coordinator=raw.get("coordinator", ""))
        old = self.assignment
        self.assignment = new
        if (old is None or old.generation != new.generation) \
                and self.on_assignment is not None:
            self.on_assignment(new)

    def join(self) -> ShardAssignment:
        payload = self._post("/control/join", {
            "host_id": self.host_id, "address": self.address,
            "n_devices": self.n_devices,
            "health": self.health_source()})
        self._apply(payload)
        assert self.assignment is not None
        return self.assignment

    def heartbeat_sync(self) -> tuple[ShardAssignment | None, bool]:
        """One synchronous heartbeat; returns (assignment, changed).
        The polling hand-off point for hosts that gate their SPMD
        launch on the group reaching a target size."""
        before = (self.assignment.generation
                  if self.assignment is not None else -1)
        self._heartbeat_once()
        after = (self.assignment.generation
                 if self.assignment is not None else -1)
        return self.assignment, after != before

    def _heartbeat_once(self) -> None:
        generation = (self.assignment.generation
                      if self.assignment is not None else -1)
        try:
            payload = self._post("/control/heartbeat", {
                "host_id": self.host_id, "generation": generation,
                "health": self.health_source()})
        except Exception as exc:
            # leader unreachable: the circuit breaker is already
            # backing off — keep the last assignment and keep serving
            if self.logger:
                self.logger.warn(f"control-plane heartbeat failed: {exc}")
            return
        if payload.get("rejoin"):
            try:
                self.join()
            except Exception as exc:
                if self.logger:
                    self.logger.warn(f"rejoin failed: {exc}")
            return
        self._apply(payload)

    def start(self) -> None:
        """Begin joining + heartbeating. A leader that is not up yet
        must not be fatal (rolling restarts bring workers up first):
        the thread keeps retrying the join with backoff until it
        lands, then heartbeats."""
        self._running = True
        try:
            self.join()
        except Exception as exc:
            if self.logger:
                self.logger.warn(
                    f"control-plane join failed, will retry: {exc}")

        def run() -> None:
            while self._running:
                time.sleep(self.heartbeat_interval_s)
                if not self._running:
                    return
                if self.assignment is None:
                    try:
                        self.join()
                    except Exception as exc:
                        if self.logger:
                            self.logger.warn(f"join retry failed: {exc}")
                else:
                    self._heartbeat_once()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"worker-{self.host_id}")
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(self.heartbeat_interval_s * 2 + 1)
            self._thread = None
