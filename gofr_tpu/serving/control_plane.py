"""Multi-host serving control plane: join, shard assignment, health
gossip, failure detection, elastic regeneration.

SURVEY §7 stage 8's host-coordination layer. On TPU pods the *data
plane* is a single SPMD program — XLA collectives over ICI move the
tensors, and ``jax.distributed`` launches every host into one runtime
(see :func:`ShardAssignment.jax_initialize_args`). What that runtime
does NOT provide is the service-level lifecycle around it: who is in
the serving group, which process is which rank, how a dead host is
detected, and how survivors agree to relaunch. The reference's analog
is its service client + gRPC control plane
(/root/reference/pkg/gofr/service/new.go:68, grpc.go:89); this module
plays that role with the framework's own building blocks — the leader
is a set of HTTP routes on an :class:`~gofr_tpu.app.App`, workers dial
it through :func:`~gofr_tpu.service.new_http_service` (circuit
breaker + retry included).

Protocol (all JSON over the framework's HTTP):

- ``POST /control/join`` {host_id, address, n_devices, health?}
  -> {generation, assignment} and bumps the generation: membership
  changed, every host must re-coordinate.
- ``POST /control/heartbeat`` {host_id, generation, health?, summary?,
  metrics?} -> {ok, generation, assignment} — a worker heartbeating
  with a stale generation learns its new assignment right there
  (elastic restart: ranks are contiguous again after an eviction or a
  join). ``summary`` is the worker's flight-recorder digest
  (p50/p95 pass duration, occupancy, queue depth, tokens/s) and
  ``metrics`` its ``Manager.snapshot()`` — the fleet observability
  plane rides the heartbeats the protocol already pays for.
- ``GET /control/topology`` -> members, assignments, gossiped health —
  also surfaced through the leader app's health endpoint.
- ``GET /control/fleet/metrics`` -> the FEDERATED Prometheus surface:
  every member's snapshot with ``host``/``rank`` labels plus the
  leader's computed ``app_fleet_*`` series, one scrape for the group.
- ``GET /debug/fleet`` -> consolidated JSON: per-host flight
  summaries, pass/occupancy skew, stragglers, counter totals.

Failure detection: the leader sweeps heartbeat deadlines; a host that
misses ``eviction_misses`` intervals is evicted and the generation
bumps. A heartbeat gossiping DEGRADED health (e.g. the engine stall
watchdog fired) is evicted IMMEDIATELY when
``FleetConfig.evict_degraded`` — survivors re-rank through the normal
elastic-regeneration path instead of waiting out heartbeat silence.
Workers detect leader loss through the service client's circuit
breaker and keep retrying with backoff.

Leader high availability (``FleetConfig.leader_candidates``): ranked
standby leaders share the candidate list; rank 0 boots active at epoch
1, higher ranks boot standby at epoch 0. Election is **worker-driven
and deterministic** — a worker that misses
``missed_acks_before_failover`` heartbeat acks (or sees typed
``stale_leader`` / ``not_leader`` evidence) probes
``GET /control/leader`` across the candidates in rank order and elects
by a pure function of the probe results: the active candidate with the
highest epoch wins (ties to the lowest rank); with no active candidate
the lowest-ranked live one is activated by a takeover join at
``max(epochs seen) + 1``. Epochs are counters — no wall clock, no RNG
— so every failover drill reproduces under bisect. Every control
message carries the epoch both ways and both sides **fence**: a leader
receiving a higher epoch than it holds refuses the write with a typed
``stale_leader`` 409, counts ``app_fleet_stale_leader_rejects``, and
demotes itself; a worker receiving a lower-epoch ack rejects it and
re-discovers. Split-brain is impossible by construction. The new
leader rebuilds membership, prefix digests, goodput federation and
routing purely from the next heartbeat round (workers beat immediately
after a failover join) — no replicated log.

Straggler detection: the leader derives max/median skew of p95 pass
duration and mean occupancy across members from the heartbeat
summaries, exposes them as ``app_fleet_pass_skew`` /
``app_fleet_occupancy_skew`` / ``app_fleet_straggler_ratio`` gauges,
and WARN-logs the offending host when skew crosses
``FleetConfig.straggler_ratio``.

Integrity divergence voting: heartbeat summaries also carry each
host's golden-canary probe digests (serving/integrity.py). With
``FleetConfig.integrity_quorum`` or more hosts reporting a digest for
the same golden probe the leader majority-votes — an on-host probe
cannot catch corruption that also corrupted its sealed expectation,
but the fleet majority can. A minority host is QUARANTINED: the
routing view stops advertising it UP (the data-plane router drops it
and fails in-flight work over via typed retries), one
``fleet.integrity_divergence`` event + incident bundle opens per
episode, and the host rejoins after
``FleetConfig.integrity_clean_probes`` consecutive new agreeing
probes. See docs/operations.md "A host is returning garbage".

Cross-host trace stitching: join/heartbeat RPCs carry ``traceparent``
(the worker wraps each RPC in a ``control.*`` span; the service client
injects the header; the leader's tracing middleware continues the
trace), and both sides set the process-wide fleet context
(host_id/rank/generation) that the tracer and logger merge into every
span and log record — one trace and one grep correlate leader and
worker.

Everything here is host-side assembly of data the engine already
records (PR 3's zero-hot-path-perturbation invariant): snapshots and
summaries are read on heartbeat threads, skew is leader-side
arithmetic, and the stall watchdog polls ``health_check()``.
"""

from __future__ import annotations

import random
import statistics
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

from ..http.errors import (ErrorInvalidParam, ErrorServiceUnavailable,
                           HTTPError)
from ..logging.logger import WARN, set_fleet_context
from ..metrics.registry import merge_snapshots, render_federated
from ..tracing.tracer import current_span
from .events import FleetEventMerger, IncidentDetector, resolve_ledger
from .faults import NO_FAULTS, resolve_plan


class StaleGeneration(HTTPError):
    """The leader no longer knows this host: a 409 telling the worker
    to rejoin (which returns the fresh assignment)."""

    status_code = 409


class StaleLeader(HTTPError):
    """Epoch fence: the caller presented a HIGHER epoch than this
    leader holds, proving a newer leader was elected while this one
    was away — the write is refused and this leader demotes itself.
    409, not 503: the conflict is permanent for this epoch, retrying
    the same leader is pointless (re-discover instead)."""

    status_code = 409
    log_level = WARN


class NotLeader(ErrorServiceUnavailable):
    """A control or data-plane request hit a standby candidate: a
    typed 503 whose details carry the epoch and candidate ranks so
    the caller can walk ``GET /control/leader`` and re-dial."""


@dataclass
class FleetConfig:
    """Knobs for the fleet observability plane (docs/configs.md)."""

    #: workers attach ``Manager.snapshot()`` to heartbeats and the
    #: leader serves the federated surface; off = heartbeats carry
    #: only health + flight summary (cheaper wire, no /fleet/metrics
    #: series for this worker)
    federation: bool = True
    #: a host whose p95 pass duration exceeds this multiple of the
    #: fleet median is flagged a straggler (gauge + WARN)
    straggler_ratio: float = 2.0
    #: evict a member the moment its heartbeat gossips DEGRADED
    #: health (stall watchdog escalation) instead of waiting for
    #: heartbeat silence
    evict_degraded: bool = True
    #: ranked leader candidate base URLs for HA; index = rank. Empty
    #: (the default) is single-leader mode: the one leader is active
    #: and workers never run the discovery walk
    leader_candidates: tuple = ()
    #: convergence budget a takeover advertises to clients (the
    #: Retry-After on ``leader_takeover`` 503s); response shaping
    #: only — never an election input
    leader_lease_s: float = 10.0
    #: consecutive heartbeat acks a worker may miss before it runs
    #: the candidate discovery walk (typed stale_leader / not_leader
    #: evidence fails over immediately, without waiting this out)
    missed_acks_before_failover: int = 3
    #: minimum hosts reporting a digest for the SAME golden probe
    #: before the leader majority-votes on it (integrity divergence
    #: detection needs a tie-breaker: with 2 hosts a mismatch names
    #: nobody, with 3 the odd one out is the outlier)
    integrity_quorum: int = 3
    #: consecutive NEW (seq-advanced) majority-agreeing probe
    #: observations a quarantined host must post before the leader
    #: lifts the quarantine and the router routes to it again
    integrity_clean_probes: int = 2


def engine_fleet_sources(engine: Any) -> tuple[Callable[[], dict],
                                               Callable[[], dict],
                                               Callable[[], dict | None]]:
    """(health, summary, metrics) heartbeat sources for a WorkerAgent
    wrapping a serving engine: gossip-sized health, the flight
    recorder's fleet digest, and the attached metrics manager's
    snapshot. All host-side reads — safe at heartbeat cadence."""

    def health() -> dict:
        h = engine.health_check()
        out = {"status": h.get("status", "UP")}
        for key in ("error", "stalled_for_s", "stalls", "restarts",
                    "last_crash", "stranded_slots"):
            if key in h:
                out[key] = h[key]
        return out

    def summary() -> dict:
        recorder = getattr(engine, "recorder", None)
        out = recorder.fleet_summary() if recorder is not None \
            and recorder.enabled else {}
        out["active_slots"] = sum(r is not None for r in engine.active)
        out["waiting"] = engine.waiting.qsize()
        out["total_generated"] = engine.total_generated
        return out

    def metrics() -> dict | None:
        manager = getattr(engine, "metrics", None)
        if manager is None or not hasattr(manager, "snapshot"):
            return None
        return manager.snapshot()

    return health, summary, metrics


@dataclass
class ShardAssignment:
    """One host's slice of the serving group."""

    host_id: str
    rank: int
    world_size: int
    n_devices: int
    generation: int
    coordinator: str  # host:port every jax.distributed process dials

    def jax_initialize_args(self) -> dict[str, Any]:
        """kwargs for ``jax.distributed.initialize`` — the hand-off
        point from control plane to SPMD data plane."""
        return {"coordinator_address": self.coordinator,
                "num_processes": self.world_size,
                "process_id": self.rank}

    def to_dict(self) -> dict[str, Any]:
        return {"host_id": self.host_id, "rank": self.rank,
                "world_size": self.world_size,
                "n_devices": self.n_devices,
                "generation": self.generation,
                "coordinator": self.coordinator}


@dataclass
class _Member:
    host_id: str
    address: str
    n_devices: int
    last_seen: float
    health: dict = field(default_factory=dict)
    #: flight-recorder digest from the last heartbeat (straggler math)
    summary: dict = field(default_factory=dict)
    #: last attached Manager.snapshot() (metrics federation)
    metrics_snapshot: dict | None = None


#: gauge/counter families the leader writes; registered by the
#: container's framework set and (belt-and-braces) on install()
_FLEET_GAUGES = (
    ("app_fleet_world_size", "control-plane serving-group members"),
    ("app_fleet_generation", "control-plane membership generation"),
    ("app_fleet_pass_skew",
     "max/median p95 pass duration across hosts (1 = balanced)"),
    ("app_fleet_occupancy_skew",
     "max/median mean batch occupancy across hosts"),
    ("app_fleet_straggler_ratio",
     "fraction of hosts whose p95 pass duration exceeds "
     "straggler_ratio x the fleet median"),
    ("app_fleet_goodput_ratio",
     "fleet-wide useful device time over busy device time, summed "
     "across member heartbeat goodput digests"),
    ("app_fleet_leader_epoch",
     "this leader's election epoch (monotone across failovers; the "
     "fleet-wide max identifies the active leader)"),
    ("app_fleet_quarantined_hosts",
     "hosts currently quarantined by the integrity divergence vote "
     "(routed traffic share held at zero until they rejoin)"),
)
_FLEET_COUNTERS = (
    ("app_fleet_evictions",
     "hosts evicted from the serving group (by reason label)"),
    ("app_fleet_heartbeats", "control-plane heartbeats received"),
    ("app_fleet_failovers",
     "leader failovers observed (by reason label: missed_acks, "
     "stale_leader, not_leader on workers; takeover on the leader "
     "that activated)"),
    ("app_fleet_stale_leader_rejects",
     "control writes refused by epoch fencing: a revived stale "
     "leader rejecting (and demoting on) higher-epoch messages"),
    ("app_fleet_quarantines",
     "integrity-divergence quarantine actions (by action label: "
     "quarantine when the vote names an outlier, rejoin when its "
     "clean-probe streak clears it)"),
)


class ControlPlaneLeader:
    """Leader state + the routes that expose it. Attach to any App:

    >>> leader = ControlPlaneLeader(coordinator="10.0.0.1:8476")
    >>> leader.install(app)        # POST /control/join, /control/heartbeat
    """

    def __init__(self, *, coordinator: str = "",
                 heartbeat_interval_s: float = 2.0,
                 eviction_misses: int = 3,
                 fleet: FleetConfig | None = None,
                 host_id: str = "",
                 rank: int = 0,
                 metrics: Any = None,
                 logger: Any = None,
                 faults: Any = None,
                 events: Any = None) -> None:
        self.coordinator = coordinator
        self.heartbeat_interval_s = heartbeat_interval_s
        self.eviction_misses = eviction_misses
        self.fleet = fleet if fleet is not None else FleetConfig()
        self.host_id = host_id
        self.metrics = metrics
        self.logger = logger
        #: deterministic fault plan for the leader-side HA sites
        #: leader_down / leader_partition / stale_epoch_replay
        self.faults = resolve_plan(faults)
        #: this candidate's position in fleet.leader_candidates; rank
        #: 0 boots active at epoch 1, higher ranks boot standby at
        #: epoch 0 awaiting a takeover join
        self.rank = int(rank)
        self.epoch = 1 if self.rank == 0 else 0
        self.active = self.rank == 0
        #: activated-by-takeover and no member heartbeat landed yet:
        #: the router answers typed leader_takeover 503s until the
        #: first join converges the rebuilt state (count-based, not
        #: clock-based — deterministic)
        self._took_over = False
        self._stale_rejects = 0
        self.generation = 0
        self._members: dict[str, _Member] = {}
        self._stragglers: set[str] = set()
        #: hosts quarantined by the integrity divergence vote:
        #: host_id -> {golden_id, digest, majority, voters, last_seq,
        #: clean}. Membership here IS the episode latch — the
        #: divergence event/bundle fire exactly once, on entry — and
        #: the routing view reports these hosts QUARANTINED
        self._quarantined: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._sweeper: threading.Thread | None = None
        self._running = False
        #: callbacks (host_id, reason) fired after a member leaves the
        #: group for any reason (leave, sweep, degraded, scale_down) —
        #: the fleet router drops its session-affinity entries here
        self.evict_listeners: list = []
        #: callbacks (host_id, action) fired on integrity quarantine
        #: transitions, action in {"quarantine", "rejoin"} — the fleet
        #: router drops affinity to a quarantined host and counts the
        #: action in its debug state
        self.quarantine_listeners: list = []
        #: extra named () -> dict blocks merged into fleet_status()
        #: (``/debug/fleet``) — the router publishes its state here
        self.status_sources: dict[str, Any] = {}
        #: the leader's own event ledger: failovers, fence rejects,
        #: evictions, stragglers. app.serve_fleet_leader passes a
        #: colocated engine's ledger in so one process shares one ring
        self.events = resolve_ledger(events, host=host_id,
                                     metrics=metrics)
        #: per-host heartbeat event digests merged into the
        #: skew-corrected fleet timeline (``GET /debug/fleet/events``);
        #: evicted hosts' events are retained — the bundle for an
        #: incident that killed a host must still show its last acts
        self.merger = FleetEventMerger()
        #: incident auto-snapshot riding the merged timeline — the
        #: ``failover`` trigger fires here when a takeover commits
        self.incidents = IncidentDetector(self.events.config,
                                          ledger=self.events,
                                          host=host_id, logger=logger)
        self.incidents.timeline_source = self._incident_timeline
        self.incidents.sources.update({
            "leadership": self.leadership,
            "fleet": self.fleet_status,
        })
        if metrics is not None:
            self._register_metrics(metrics)

    # ---------------------------------------------------- fleet metrics
    @staticmethod
    def _register_metrics(metrics: Any) -> None:
        for name, desc in _FLEET_GAUGES:
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        for name, desc in _FLEET_COUNTERS:
            if metrics.get(name) is None:
                metrics.new_counter(name, desc)

    def _set_membership_gauges(self) -> None:
        if self.metrics is None:
            return
        self.metrics.set_gauge("app_fleet_world_size",
                               float(len(self._members)))
        self.metrics.set_gauge("app_fleet_generation",
                               float(self.generation))
        self.metrics.set_gauge("app_fleet_leader_epoch",
                               float(self.epoch))

    # ------------------------------------------------------- leadership
    def leadership(self) -> dict:
        """The lease state, consistently snapshotted: served by
        ``GET /control/leader`` and read by the router's data-plane
        gate."""
        with self._lock:
            return {"active": self.active, "epoch": self.epoch,
                    "rank": self.rank, "host_id": self.host_id,
                    "converging": self.active and self._took_over
                    and not self._members,
                    "candidates": list(self.fleet.leader_candidates),
                    "stale_rejects": self._stale_rejects}

    def ensure_active(self, worker_epoch: int = -1) -> bool:
        """Takeover activation: a worker that lost the old leader
        elects this candidate by joining with ``takeover``. Activates
        at ``max(own epoch, worker epoch) + 1`` — strictly above
        anything either side has seen, so every subsequent control
        message fences the old leader. Idempotent under concurrent
        takeover joins: once one wins, later joins with lower or
        equal evidence see ``active`` with a higher epoch and do not
        re-bump. Counts and epochs only — no clocks, no RNG."""
        with self._lock:
            if self.active and self.epoch > int(worker_epoch):
                return False  # an earlier takeover already won
            self.epoch = max(self.epoch, int(worker_epoch)) + 1
            self.active = True
            self._took_over = True
            epoch = self.epoch
        if self.metrics is not None:
            self.metrics.set_gauge("app_fleet_leader_epoch",
                                   float(epoch))
            self.metrics.increment_counter("app_fleet_failovers",
                                           reason="takeover")
        if self.logger:
            self.logger.warn("standby leader activated by takeover",
                             epoch=epoch, rank=self.rank)
        # The takeover join arrives over HTTP, so the middleware has a
        # server span open carrying the worker's trace — stamp its
        # trace_id onto the failover record and the incident so the
        # bundle resolves back to the exact request that elected us.
        span = current_span()
        trace_id = span.trace_id if span is not None else None
        self.events.emit("fleet.epoch_bump", epoch=epoch,
                         cause="takeover", trace_id=trace_id)
        self.events.emit("fleet.failover", severity="error",
                         cause="takeover", epoch=epoch, rank=self.rank,
                         trace_id=trace_id)
        self.incidents.trigger(
            "failover", epoch=epoch, trace_id=trace_id,
            cause=f"standby rank {self.rank} activated at epoch "
                  f"{epoch} by worker takeover")
        return True

    def _fence(self, worker_epoch: int) -> None:
        """Epoch fencing for control-plane writes. A request carrying
        a higher epoch than this leader holds proves a newer leader
        was elected while this one was away: refuse the write with a
        typed ``stale_leader`` 409, count it, and demote to standby —
        a revived old leader can never accept state, so there is no
        split brain to reconcile. A standby (including an already-
        demoted leader) refuses non-takeover writes with a typed
        ``not_leader`` 503 naming the candidate ranks, whatever epoch
        the caller carries — it never claimed the lease, so there is
        nothing stale to demote. Callers with no epoch (pre-HA
        workers) pass -1, which never out-ranks an active leader."""
        with self._lock:
            if self.active and worker_epoch > self.epoch:
                self.active = False
                self._stale_rejects += 1
                verdict, epoch = "stale", self.epoch
            elif not self.active:
                verdict, epoch = "standby", self.epoch
            else:
                return
        if verdict == "stale":
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_fleet_stale_leader_rejects")
            if self.logger:
                self.logger.warn(
                    "stale leader fenced: refusing control write and "
                    "demoting to standby", epoch=epoch,
                    caller_epoch=worker_epoch)
            self.events.emit("fleet.fence_reject", severity="warn",
                             cause="stale_leader", epoch=epoch,
                             caller_epoch=worker_epoch)
            raise StaleLeader(
                f"stale leader: caller epoch {worker_epoch} is ahead "
                f"of this leader's epoch {epoch}",
                details={"code": "stale_leader", "epoch": epoch})
        raise NotLeader(
            "not the active leader; walk GET /control/leader across "
            "the candidates and re-dial",
            details={"code": "not_leader", "epoch": epoch,
                     "candidates": list(self.fleet.leader_candidates)},
            headers={"Retry-After": "1"})

    # ------------------------------------------------------------ state
    def _ranks_locked(self) -> dict[str, int]:
        """THE rank mapping: deterministic contiguous ranks sorted by
        host_id, so every caller computes the same view for a given
        membership. Both assignments and topology derive from here."""
        return {h: i for i, h in enumerate(sorted(self._members))}

    def _assignment_locked(self, host_id: str) -> ShardAssignment:
        ranks = self._ranks_locked()
        return ShardAssignment(
            host_id=host_id, rank=ranks[host_id],
            world_size=len(ranks),
            n_devices=self._members[host_id].n_devices,
            generation=self.generation, coordinator=self.coordinator)

    def join(self, host_id: str, address: str, n_devices: int,
             health: dict | None = None, *, epoch: int = -1,
             takeover: bool = False) -> ShardAssignment:
        if not host_id:
            raise ErrorInvalidParam("host_id")
        if takeover:
            self.ensure_active(epoch)
        else:
            self._fence(epoch)
        with self._lock:
            self.generation += 1  # membership changed for everyone
            self._members[host_id] = _Member(
                host_id=host_id, address=address,
                n_devices=max(1, int(n_devices)),
                last_seen=time.time(), health=dict(health or {}))
            # first member after a takeover: the rebuilt view is live,
            # stop answering the data plane with leader_takeover 503s
            self._took_over = False
            assignment = self._assignment_locked(host_id)
        self._set_membership_gauges()
        if self.logger:
            self.logger.info(
                "host joined serving group", host=host_id,
                rank=assignment.rank, world=assignment.world_size,
                generation=self.generation)
        return assignment

    def heartbeat(self, host_id: str, generation: int,
                  health: dict | None = None,
                  summary: dict | None = None,
                  metrics_snapshot: dict | None = None,
                  address: str = "", epoch: int = -1,
                  events: dict | None = None
                  ) -> tuple[ShardAssignment | None, bool]:
        """-> (assignment, changed): ``changed`` is True when the
        worker's view was stale — its signal to re-coordinate.
        ``assignment`` is None when the heartbeat itself got the host
        evicted (DEGRADED health under ``FleetConfig.evict_degraded``)
        — the route answers with an eviction notice, not a 409, so
        the agent backs off instead of instantly rejoining wedged."""
        self._fence(epoch)
        degraded = False
        with self._lock:
            member = self._members.get(host_id)
            if member is None:
                raise StaleGeneration("unknown host: rejoin required")
            member.last_seen = time.time()
            if address and member.address != address:
                # ephemeral-port workers learn their dial address only
                # once their server binds — adopt it from the beat so
                # the data-plane router can reach them
                member.address = address
            if health is not None:
                member.health = dict(health)
            if summary is not None:
                member.summary = dict(summary)
            if metrics_snapshot is not None:
                member.metrics_snapshot = metrics_snapshot
            # DEGRADED (the stall-watchdog escalation) evicts NOW so
            # survivors re-rank; DOWN keeps gossiping — a dead engine
            # whose agent still heartbeats stays visible to operators
            # in topology/health rather than silently vanishing
            status = member.health.get("status", "UP")
            if status == "DEGRADED" and self.fleet.evict_degraded:
                degraded = True
            else:
                assignment = self._assignment_locked(host_id)
                changed = generation != self.generation
        if events:
            # the event-digest piggyback: fold this host's newest
            # events (and its wall clock, for the skew estimate) into
            # the fleet timeline
            self.merger.ingest(host_id, events)
        if self.metrics is not None:
            self.metrics.increment_counter("app_fleet_heartbeats",
                                           host=host_id)
        if degraded:
            self.evict(host_id, reason="degraded")
            return None, True
        self._recompute_skew()
        self._vote_integrity()
        return assignment, changed

    def evict(self, host_id: str, reason: str = "manual") -> None:
        with self._lock:
            if self._members.pop(host_id, None) is None:
                return
            self.generation += 1
            self._stragglers.discard(host_id)
            # an evicted host's quarantine episode ends with it — a
            # rejoin starts from a clean slate (fresh digests re-vote)
            self._quarantined.pop(host_id, None)
        self._set_membership_gauges()
        if self.metrics is not None:
            self.metrics.increment_counter("app_fleet_evictions",
                                           reason=reason)
        if self.logger:
            self.logger.warn("host evicted from serving group",
                             host=host_id, reason=reason,
                             generation=self.generation)
        self.events.emit("fleet.evict", severity="warn", cause=reason,
                         epoch=self.epoch, evicted=host_id,
                         generation=self.generation)
        for listener in list(self.evict_listeners):
            try:
                listener(host_id, reason)
            except Exception:
                pass  # a broken listener must not block membership

    def add_evict_listener(self, fn: Any) -> None:
        self.evict_listeners.append(fn)

    def add_quarantine_listener(self, fn: Any) -> None:
        self.quarantine_listeners.append(fn)

    def routing_view(self) -> list[dict]:
        """Snapshot for the data-plane router: one dict per member
        with the address to dial, health status, and the latest
        heartbeat summary (queue depth, pass timings, prefix digest).
        An integrity-quarantined host reports QUARANTINED here — the
        router only routes to UP members, so quarantine needs no
        router-side special case to stop traffic."""
        with self._lock:
            return [{"host_id": m.host_id, "address": m.address,
                     "status": "QUARANTINED"
                     if m.host_id in self._quarantined
                     else m.health.get("status", "UP"),
                     "summary": dict(m.summary)}
                    for m in self._members.values()]

    def topology(self) -> dict[str, Any]:
        with self._lock:
            ranks = self._ranks_locked()
            return {
                "generation": self.generation,
                "epoch": self.epoch,
                "active": self.active,
                "world_size": len(self._members),
                "members": {
                    m.host_id: {"address": m.address,
                                "n_devices": m.n_devices,
                                "rank": ranks[m.host_id],
                                "last_seen": m.last_seen,
                                "health": m.health}
                    for m in self._members.values()},
            }

    def health_check(self) -> dict[str, Any]:
        topo = self.topology()
        degraded = [h for h, m in topo["members"].items()
                    if m["health"].get("status") not in (None, "UP")]
        status = "UP" if not degraded else "DEGRADED"
        return {"status": status,
                "details": {"generation": topo["generation"],
                            "world_size": topo["world_size"],
                            "degraded_hosts": degraded}}

    # ------------------------------------------------------- stragglers
    @staticmethod
    def _skew(values: dict[str, float]) -> tuple[float, str | None]:
        """max/median of per-host values -> (skew, worst host). 1.0
        when balanced or under 2 hosts report."""
        if len(values) < 2:
            return 1.0, None
        med = statistics.median(values.values())
        if med <= 0:
            return 1.0, None
        worst = max(values, key=values.get)
        return values[worst] / med, worst

    @staticmethod
    def _dominant_waste(waste: Mapping | None) -> str | None:
        """Largest waste cause from a heartbeat summary's ``waste_s``
        map — the leader's one-word answer to WHY a host is slow."""
        if not isinstance(waste, Mapping) or not waste:
            return None
        cause = max(waste, key=lambda c: float(waste.get(c) or 0.0))
        return cause if float(waste.get(cause) or 0.0) > 0 else None

    def _recompute_skew(self) -> dict:
        """Leader-side straggler math over the latest heartbeat
        summaries: pure host arithmetic, called at heartbeat cadence.
        Returns the fleet digest served by ``/debug/fleet``."""
        with self._lock:
            p95s = {h: float(m.summary["pass_p95_s"])
                    for h, m in self._members.items()
                    if isinstance(m.summary.get("pass_p95_s"),
                                  (int, float))}
            occs = {h: float(m.summary["occupancy_mean"])
                    for h, m in self._members.items()
                    if isinstance(m.summary.get("occupancy_mean"),
                                  (int, float))}
            # goodput federation: heartbeat summaries carry each
            # host's busy/useful/waste digest (FlightRecorder.
            # fleet_summary via the engine's GoodputMeter)
            goodputs = {h: {"busy_s": float(m.summary["busy_s"]),
                            "useful_s": float(
                                m.summary.get("useful_s", 0.0)),
                            "waste_s": dict(m.summary.get("waste_s")
                                            or {})}
                        for h, m in self._members.items()
                        if isinstance(m.summary.get("busy_s"),
                                      (int, float))}
            # cost federation: heartbeat summaries carry each host's
            # per-signature cost table (FlightRecorder.fleet_summary
            # via the engine's CostModel)
            costs = {h: dict(m.summary["costs"])
                     for h, m in self._members.items()
                     if isinstance(m.summary.get("costs"), Mapping)
                     and m.summary.get("costs")}
            world = len(self._members)
        pass_skew, worst = self._skew(p95s)
        occ_skew, _ = self._skew(occs)
        threshold = self.fleet.straggler_ratio
        med = statistics.median(p95s.values()) if len(p95s) >= 2 else 0.0
        stragglers = sorted(h for h, v in p95s.items()
                            if med > 0 and v > threshold * med)
        # Signature-normalized straggler mode: the raw p95 comparison
        # above confounds "this host is slow" with "this host happens
        # to serve heavier shapes" — a host decoding at window 2048
        # legitimately posts fatter passes than one at 512. When >=2
        # hosts federate cost tables, compare each host's mean pass
        # cost for the SAME dispatch signature against the fleet
        # median for that signature, and name the offending signature
        # so the operator lands on the kernel, not the host.
        straggler_mode = "p95"
        straggler_signatures: dict[str, str] = {}
        sig_medians: dict[str, float] = {}
        if len(costs) >= 2:
            straggler_mode = "signature"
            by_sig: dict[str, dict[str, float]] = {}
            for host, table in costs.items():
                for sig, rec in table.items():
                    if not isinstance(rec, Mapping):
                        continue
                    mean = float(rec.get("mean_s") or 0.0)
                    if mean > 0 and int(rec.get("n") or 0) >= 2:
                        by_sig.setdefault(sig, {})[host] = mean
            # per-host worst offence: (signature, ratio over median)
            worst_sig: dict[str, tuple[str, float]] = {}
            for sig, means in by_sig.items():
                if len(means) < 2:
                    continue  # nobody to compare against
                med_sig = statistics.median(means.values())
                if med_sig <= 0:
                    continue
                sig_medians[sig] = round(med_sig, 6)
                for host, mean in means.items():
                    ratio_sig = mean / med_sig
                    if (ratio_sig > threshold
                            and ratio_sig > worst_sig.get(
                                host, ("", 0.0))[1]):
                        worst_sig[host] = (sig, ratio_sig)
            stragglers = sorted(worst_sig)
            straggler_signatures = {h: s for h, (s, _) in
                                    worst_sig.items()}
        # _stragglers is also mutated by the leave/evict path under
        # _lock from HTTP handler threads; an unlocked read-modify-write
        # here (sweeper thread) can race a concurrent discard
        with self._lock:
            new = set(stragglers) - self._stragglers
            self._stragglers = set(stragglers)
        ratio = len(stragglers) / world if world else 0.0
        fleet_goodput: dict = {}
        if goodputs:
            busy = sum(g["busy_s"] for g in goodputs.values())
            useful = sum(g["useful_s"] for g in goodputs.values())
            waste: dict[str, float] = {}
            for g in goodputs.values():
                for cause, v in g["waste_s"].items():
                    waste[cause] = waste.get(cause, 0.0) + float(v or 0)
            fleet_goodput = {
                "busy_s": round(busy, 6), "useful_s": round(useful, 6),
                "waste_s": {c: round(v, 6) for c, v in waste.items()},
                "dominant_waste": self._dominant_waste(waste)}
            if busy > 0:
                fleet_goodput["goodput_ratio"] = round(useful / busy, 6)
        straggler_causes = {
            h: self._dominant_waste(goodputs.get(h, {}).get("waste_s"))
            for h in stragglers}
        if self.metrics is not None:
            self.metrics.set_gauge("app_fleet_pass_skew",
                                   round(pass_skew, 4))
            self.metrics.set_gauge("app_fleet_occupancy_skew",
                                   round(occ_skew, 4))
            self.metrics.set_gauge("app_fleet_straggler_ratio",
                                   round(ratio, 4))
            if fleet_goodput.get("goodput_ratio") is not None:
                self.metrics.set_gauge("app_fleet_goodput_ratio",
                                       fleet_goodput["goodput_ratio"])
        for host in sorted(new):
            if self.logger:
                if straggler_mode == "signature":
                    sig = straggler_signatures.get(host)
                    self.logger.warn(
                        "straggler detected: pass cost skewed off the "
                        "fleet median for a shared dispatch signature",
                        host=host, signature=sig,
                        fleet_median_s=sig_medians.get(sig or ""),
                        threshold=threshold,
                        dominant_waste=straggler_causes.get(host))
                else:
                    self.logger.warn(
                        "straggler detected: pass duration skewed off "
                        "the fleet median", host=host,
                        p95_s=p95s.get(host), median_s=round(med, 6),
                        skew=round(pass_skew, 3), threshold=threshold,
                        # why is it slow? its own waste ledger answers
                        dominant_waste=straggler_causes.get(host))
            self.events.emit(
                "fleet.straggler", severity="warn", epoch=self.epoch,
                cause=straggler_causes.get(host) or "unknown",
                straggler=host, p95_s=p95s.get(host),
                signature=straggler_signatures.get(host),
                skew=round(pass_skew, 3))
        out = {"pass_skew": round(pass_skew, 4),
               "occupancy_skew": round(occ_skew, 4),
               "straggler_ratio": round(ratio, 4),
               "stragglers": stragglers,
               "straggler_causes": straggler_causes,
               "straggler_mode": straggler_mode,
               "straggler_signatures": straggler_signatures,
               "worst_host": worst,
               "goodput": fleet_goodput,
               "threshold": threshold}
        if costs:
            out["costs"] = {"signatures": sig_medians,
                            "hosts": sorted(costs)}
        return out

    # ------------------------------------------- integrity divergence
    def _vote_integrity(self) -> dict:
        """Majority-vote the golden-probe digests riding the heartbeat
        summaries (serving/integrity.py): per golden probe id reported
        by >= ``FleetConfig.integrity_quorum`` hosts, the strict-
        majority digest is taken as fleet truth and a minority host is
        the outlier — its own probe cannot catch corruption that also
        corrupted its sealed expectation, but the fleet can. Naming an
        outlier quarantines it (entry into ``_quarantined`` is the
        once-per-episode latch: one ``fleet.integrity_divergence``
        event + one incident bundle); a quarantined host rejoins after
        ``integrity_clean_probes`` consecutive NEW (probe-seq
        advanced) majority-agreeing observations. Leader-side digest
        comparison at heartbeat cadence — counts only, no clocks, no
        RNG, so a divergence drill reproduces under bisect."""
        quorum = max(2, int(self.fleet.integrity_quorum))
        clean_needed = max(1, int(self.fleet.integrity_clean_probes))
        with self._lock:
            reports: dict[str, dict] = {}
            for h, m in self._members.items():
                integ = m.summary.get("integrity")
                if not isinstance(integ, Mapping):
                    continue
                probes = integ.get("probe_digests")
                if not isinstance(probes, Mapping) or not probes:
                    continue
                reports[h] = {
                    "digests": {str(g): str(d)
                                for g, d in probes.items()},
                    "seq": int(integ.get("seq") or 0)}
        # ballot boxes: golden id -> {host: digest}
        by_golden: dict[str, dict[str, str]] = {}
        for host, rep in reports.items():
            for gid, digest in rep["digests"].items():
                by_golden.setdefault(gid, {})[host] = digest
        votes: dict[str, dict] = {}
        outliers: dict[str, str] = {}  # host -> golden id it lost on
        agree: dict[str, bool] = {}    # host agreed with every verdict
        for gid, ballots in sorted(by_golden.items()):
            if len(ballots) < quorum:
                continue  # not enough voters to break a tie
            tally: dict[str, int] = {}
            for digest in ballots.values():
                tally[digest] = tally.get(digest, 0) + 1
            winner = max(tally, key=lambda d: tally[d])
            if tally[winner] * 2 <= len(ballots):
                # no strict majority: the fleet itself disagrees —
                # record the split, never guess an outlier from a tie
                votes[gid] = {"majority": None, "tally": tally,
                              "voters": len(ballots)}
                continue
            votes[gid] = {"majority": winner, "tally": tally,
                          "voters": len(ballots)}
            for host, digest in ballots.items():
                if digest == winner:
                    agree.setdefault(host, True)
                else:
                    agree[host] = False
                    outliers.setdefault(host, gid)
        newly: list[tuple[str, dict]] = []
        rejoined: list[tuple[str, dict]] = []
        with self._lock:
            for host, gid in outliers.items():
                rec = self._quarantined.get(host)
                if rec is not None:
                    # still dirty: restart the clean streak
                    rec["clean"] = 0
                    rec["last_seq"] = reports[host]["seq"]
                    continue
                rec = {"golden_id": gid,
                       "digest": reports[host]["digests"][gid],
                       "majority": votes[gid]["majority"],
                       "voters": votes[gid]["voters"],
                       "generation": self.generation,
                       "last_seq": reports[host]["seq"],
                       "clean": 0}
                self._quarantined[host] = rec
                newly.append((host, dict(rec)))
            for host in list(self._quarantined):
                if host in outliers or host not in agree:
                    continue  # no fresh verdict on this host
                rep = reports.get(host)
                rec = self._quarantined[host]
                # same probes as last round are not new evidence —
                # the rejoin streak counts PROBES, not heartbeats
                if rep is None or rep["seq"] <= rec.get("last_seq", -1):
                    continue
                rec["last_seq"] = rep["seq"]
                rec["clean"] = rec.get("clean", 0) + 1
                if rec["clean"] >= clean_needed:
                    rejoined.append((host, self._quarantined.pop(host)))
            quarantined = {h: dict(r)
                           for h, r in self._quarantined.items()}
        for host, rec in newly:
            if self.metrics is not None:
                self.metrics.increment_counter("app_fleet_quarantines",
                                               action="quarantine")
            if self.logger:
                self.logger.warn(
                    "host quarantined: golden-probe digest diverged "
                    "from the fleet majority — routing stops until "
                    "its clean-probe streak clears it",
                    host=host, golden_id=rec["golden_id"],
                    digest=rec["digest"], majority=rec["majority"],
                    voters=rec["voters"])
            self.events.emit(
                "fleet.integrity_divergence", severity="error",
                epoch=self.epoch, cause="probe_digest_minority",
                outlier=host, golden_id=rec["golden_id"],
                digest=rec["digest"], majority=rec["majority"],
                voters=rec["voters"])
            self.events.emit(
                "fleet.quarantine", severity="warn",
                epoch=self.epoch, cause="integrity_divergence",
                quarantined=host, action="quarantine")
            self.incidents.trigger(
                "integrity_divergence", epoch=self.epoch,
                cause=f"host {host} diverged from the fleet majority "
                      f"on golden probe {rec['golden_id']}",
                attrs=dict(rec, host=host))
            for listener in list(self.quarantine_listeners):
                try:
                    listener(host, "quarantine")
                except Exception:
                    pass  # a broken listener must not block the vote
        for host, rec in rejoined:
            if self.metrics is not None:
                self.metrics.increment_counter("app_fleet_quarantines",
                                               action="rejoin")
            if self.logger:
                self.logger.info(
                    "quarantined host rejoined: consecutive clean "
                    "golden probes agreed with the fleet majority",
                    host=host, clean=rec["clean"],
                    golden_id=rec["golden_id"])
            self.events.emit(
                "fleet.quarantine", severity="info",
                epoch=self.epoch, cause="clean_probes",
                quarantined=host, action="rejoin",
                clean=rec["clean"])
            for listener in list(self.quarantine_listeners):
                try:
                    listener(host, "rejoin")
                except Exception:
                    pass
        if self.metrics is not None:
            self.metrics.set_gauge("app_fleet_quarantined_hosts",
                                   float(len(quarantined)))
        return {"quorum": quorum,
                "clean_probes": clean_needed,
                "reporting": sorted(reports),
                "votes": votes,
                "quarantined": quarantined}

    # ------------------------------------------------------ fleet views
    def fleet_status(self) -> dict:
        """The consolidated ``/debug/fleet`` JSON: per-host flight
        summaries + gossiped health, skew/straggler digest, counter
        totals merged across hosts."""
        with self._lock:
            ranks = self._ranks_locked()
            now = time.time()
            hosts = {
                h: {"rank": ranks[h], "address": m.address,
                    "status": "QUARANTINED" if h in self._quarantined
                    else m.health.get("status", "UP"),
                    "health": dict(m.health),
                    "last_seen_age_s": round(now - m.last_seen, 3),
                    "summary": dict(m.summary),
                    "federated": m.metrics_snapshot is not None}
                for h, m in self._members.items()}
            snaps = {h: m.metrics_snapshot
                     for h, m in self._members.items()
                     if m.metrics_snapshot is not None}
            generation, world = self.generation, len(self._members)
        totals: dict[str, float] = {}
        merged = merge_snapshots(snaps)
        for name, fam in merged["metrics"].items():
            if fam.get("kind") != "counter":
                continue
            totals[name] = round(sum(float(s.get("value", 0.0))
                                     for s in fam["series"]), 6)
        # the fleet answer to "who is burning my budget": per-tenant
        # token/device totals summed across every member's heartbeat
        # snapshot (counters with identical labelsets merge by sum)
        tenant_usage: dict[str, dict[str, float]] = {}
        for name in ("app_tenant_requests", "app_tenant_prompt_tokens",
                     "app_tenant_completion_tokens",
                     "app_tenant_device_seconds"):
            fam = merged["metrics"].get(name)
            if not fam:
                continue
            for s in fam.get("series", ()):
                tenant = (s.get("labels") or {}).get("tenant", "unknown")
                bucket = tenant_usage.setdefault(tenant, {})
                bucket[name] = round(bucket.get(name, 0.0)
                                     + float(s.get("value", 0.0)), 6)
        out = {"generation": generation, "world_size": world,
               "fleet": self._recompute_skew(), "hosts": hosts,
               "integrity": self._vote_integrity(),
               "counter_totals": totals,
               "tenant_usage": tenant_usage}
        for name, source in self.status_sources.items():
            try:
                out[name] = source()
            except Exception:
                out[name] = {"error": "status source failed"}
        return out

    def _ingest_own_events(self) -> None:
        """Fold the leader's own ledger into the merged timeline (its
        clock IS the reference clock, so the offset is ~0)."""
        self.merger.ingest(self.host_id or "leader",
                           self.events.digest())

    def _incident_timeline(self, since: float,
                           until: float) -> list[dict]:
        """IncidentDetector timeline source: the merged fleet view
        around the trigger, corrected timestamps filtering."""
        self._ingest_own_events()
        return self.merger.timeline(since=since, until=until)

    def fleet_events_jsonl(self, *, kind: str | None = None,
                           since: float | None = None,
                           n: int | None = None) -> str:
        """The ``GET /debug/fleet/events`` body: versioned JSONL,
        header line first, then the skew-corrected merged timeline."""
        self._ingest_own_events()
        return self.merger.to_jsonl(kind=kind, since=since, n=n)

    def fleet_metrics_text(self) -> str:
        """The federated Prometheus exposition for
        ``GET /control/fleet/metrics``: every member's snapshot with
        ``host``/``rank`` labels (``app_fleet_*`` families excluded —
        those are leader-computed and appended once from the leader's
        own manager, so a leader that also joins as a worker never
        emits a duplicate family)."""
        self._recompute_skew()  # gauges fresh at scrape time
        with self._lock:
            ranks = self._ranks_locked()
            per_host = {}
            labels = {}
            for h, m in self._members.items():
                if m.metrics_snapshot is None:
                    continue
                metrics = {name: fam for name, fam in
                           (m.metrics_snapshot.get("metrics")
                            or {}).items()
                           if not name.startswith("app_fleet_")}
                per_host[h] = {"metrics": metrics}
                labels[h] = {"host": h, "rank": str(ranks[h])}
        text = render_federated(per_host, labels)
        if self.metrics is not None:
            text += self.metrics.render_prometheus(prefix="app_fleet_")
        return text or "\n"

    # ---------------------------------------------------------- sweeper
    def _sweep_once(self) -> None:
        deadline = time.time() - (self.heartbeat_interval_s
                                  * self.eviction_misses)
        with self._lock:  # joins mutate _members concurrently
            dead = [h for h, m in self._members.items()
                    if m.last_seen < deadline]
        for host_id in dead:
            self.evict(host_id, reason="heartbeat_timeout")

    def start(self) -> None:
        self._running = True

        def run() -> None:
            while self._running:
                self._sweep_once()
                time.sleep(self.heartbeat_interval_s / 2)

        self._sweeper = threading.Thread(target=run, daemon=True,
                                         name="control-plane-sweeper")
        self._sweeper.start()

    def stop(self) -> None:
        self._running = False

    def _trip_leader_faults(self, host_id: str) -> None:
        """Injected leader failure modes for the HA drills: an armed
        ``leader_down`` refuses every control RPC, ``leader_partition``
        refuses only the host named by its ``request=`` tag. Both look
        like a dead/unreachable leader to the worker (an untyped 503
        counts as a missed ack), never like a typed refusal."""
        if self.faults is NO_FAULTS:
            return
        if self.faults.trip("leader_down"):
            raise ErrorServiceUnavailable(
                "leader down (injected)",
                details={"code": "leader_down"})
        if self.faults.trip("leader_partition", request_id=host_id):
            raise ErrorServiceUnavailable(
                "leader partitioned from host (injected)",
                details={"code": "leader_partition"})

    # ------------------------------------------------------------ routes
    def install(self, app: Any) -> None:
        """Register the control routes and start the sweeper when the
        app starts (reference startup-hook pattern, gofr.go:359).
        Adopts the app container's metrics manager (registering the
        ``app_fleet_*`` families if absent) so the fleet gauges ride
        the leader's own /metrics port too."""
        if self.metrics is None:
            self.metrics = app.container.metrics
            self._register_metrics(self.metrics)
        self._set_membership_gauges()  # leader epoch visible from boot
        if self.host_id:
            # leader-side half of cross-host correlation: every leader
            # log/span names the host it ran on
            set_fleet_context(host_id=self.host_id)

        @app.post("/control/join")
        def join(ctx):
            body = ctx.bind() or {}
            self._trip_leader_faults(str(body.get("host_id", "")))
            assignment = self.join(
                str(body.get("host_id", "")),
                str(body.get("address", "")),
                _body_int(body, "n_devices", 1),
                body.get("health"),
                epoch=_body_int(body, "epoch", -1),
                takeover=bool(body.get("takeover", False)))
            # the assignment's generation, not a re-read of
            # self.generation: a concurrent join may have bumped it
            return {"generation": assignment.generation,
                    "assignment": assignment.to_dict(),
                    "epoch": self.epoch}

        @app.post("/control/heartbeat")
        def heartbeat(ctx):
            body = ctx.bind() or {}
            self._trip_leader_faults(str(body.get("host_id", "")))
            assignment, changed = self.heartbeat(
                str(body.get("host_id", "")),
                _body_int(body, "generation", -1),
                body.get("health"),
                body.get("summary"),
                body.get("metrics") if self.fleet.federation else None,
                address=str(body.get("address", "")),
                epoch=_body_int(body, "epoch", -1),
                events=body.get("events"))
            epoch_out = self.epoch
            if self.faults is not NO_FAULTS \
                    and self.faults.trip("stale_epoch_replay"):
                # injected replayed/rolled-back ack: the worker-side
                # fence must reject it and re-discover
                epoch_out = max(0, epoch_out - 1)
            if assignment is None:  # evicted on this very heartbeat
                return {"ok": False, "evicted": True,
                        "generation": self.generation,
                        "epoch": epoch_out}
            return {"ok": True, "changed": changed,
                    "generation": assignment.generation,
                    "assignment": assignment.to_dict(),
                    "epoch": epoch_out}

        @app.post("/control/leave")
        def leave(ctx):
            # graceful deregistration (SIGTERM drain): the departing
            # worker tells the leader NOW instead of making survivors
            # wait out heartbeat silence before re-ranking
            body = ctx.bind() or {}
            host_id = str(body.get("host_id", ""))
            if not host_id:
                raise ErrorInvalidParam("host_id")
            self._trip_leader_faults(host_id)
            self._fence(_body_int(body, "epoch", -1))
            self.evict(host_id, reason="leave")
            return {"ok": True, "generation": self.generation,
                    "epoch": self.epoch}

        @app.get("/control/leader")
        def leader_info(ctx):
            # discovery, safe on any candidate active or standby: the
            # redirect contract is "probe the ranked candidates, dial
            # the active one with the highest epoch" — workers, the
            # service client's resolve_leader, and operators all walk
            # the same door (docs/operations.md "Losing the leader").
            # An injected leader_down refuses probes too — a down
            # leader must look dead to the discovery walk (a
            # partition stays asymmetric: probes carry no host_id)
            if self.faults is not NO_FAULTS \
                    and self.faults.trip("leader_down"):
                raise ErrorServiceUnavailable(
                    "leader down (injected)",
                    details={"code": "leader_down"})
            info = self.leadership()
            info["heartbeat_interval_s"] = self.heartbeat_interval_s
            return info

        @app.get("/control/topology")
        def topology(ctx):
            return self.topology()

        @app.get("/control/fleet/metrics")
        def fleet_metrics(ctx):
            from ..http.responder import ResponseData
            return ResponseData(
                status=200, body=self.fleet_metrics_text().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8")

        @app.get("/debug/fleet")
        def debug_fleet(ctx):
            return self.fleet_status()

        @app.get("/debug/fleet/events")
        def debug_fleet_events(ctx):
            # same query contract as GET /debug/events, served over
            # the merged skew-corrected fleet timeline
            from ..http.response import File
            kind = ctx.param("kind") or None
            raw_since = ctx.param("since")
            since = None
            if raw_since not in (None, ""):
                try:
                    since = float(raw_since)
                except (TypeError, ValueError):
                    raise ErrorInvalidParam("since")
            n = _body_int({"n": ctx.param("n") or 0}, "n", 0)
            n = max(0, min(1 << 20, n)) or None
            body = self.fleet_events_jsonl(kind=kind, since=since, n=n)
            return File(content=body.encode(),
                        content_type="application/jsonl; charset=utf-8")

        @app.get("/debug/fleet/incidents")
        def debug_fleet_incidents(ctx):
            # leader-side incident spool (failover bundles carry the
            # MERGED fleet timeline); ?id= fetches one full bundle
            incident_id = ctx.param("id") or None
            if incident_id is None:
                return {"incidents": self.incidents.list(),
                        "detector": self.incidents.state()}
            bundle = self.incidents.get(incident_id)
            if bundle is None:
                from ..http.errors import ErrorEntityNotFound
                raise ErrorEntityNotFound(f"incident {incident_id!r}")
            return bundle

        app.container.register_health_check("control_plane", self)

        @app.on_start
        def _start_sweeper():
            self.start()

        app.on_shutdown(self.stop)


def _body_int(body: Mapping[str, Any], key: str, default: int) -> int:
    """Optional integer field of a control-plane body: absent takes
    the default, garbage draws a typed 400 naming the field instead
    of surfacing as an internal error."""
    value = body.get(key, default)
    try:
        return int(value)
    except (TypeError, ValueError):
        raise ErrorInvalidParam(key)


def _typed_reject(response) -> tuple[str, dict]:
    """Pull the typed error code + details out of a control-plane
    reject (the ``{"error": {"message", "details"}}`` envelope).
    Unparseable bodies degrade to ``("", {})`` — the caller falls back
    on status-code semantics."""
    try:
        doc = response.json() or {}
        details = ((doc.get("error") or {}).get("details") or {})
        return str(details.get("code") or ""), details
    except (ValueError, AttributeError, TypeError):
        return "", {}


class WorkerAgent:
    """A serving host's side of the protocol: join once, heartbeat on a
    thread, and invoke ``on_assignment`` every time the generation
    changes — the hook where the host tears down and relaunches its
    SPMD program with the new rank/world (elastic restart).

    With a multi-candidate ``FleetConfig.leader_candidates`` the agent
    also runs the HA failover protocol: count missed heartbeat acks,
    and after ``missed_acks_before_failover`` of them walk the ranked
    candidates (``GET /control/leader``), elect deterministically
    (:meth:`_choose_candidate` — counts/epochs only, no clocks, no
    RNG), re-dial, and takeover-join so the winner rebuilds its state
    from this worker's very next heartbeat."""

    def __init__(self, leader_url: str, *, host_id: str,
                 address: str | Callable[[], str] = "",
                 n_devices: int = 1,
                 heartbeat_interval_s: float = 2.0,
                 on_assignment: Callable[[ShardAssignment], None]
                 | None = None,
                 health_source: Callable[[], dict] | None = None,
                 summary_source: Callable[[], dict] | None = None,
                 metrics_source: Callable[[], dict | None] | None = None,
                 fleet: FleetConfig | None = None,
                 join_backoff_max_s: float = 30.0,
                 tracer: Any = None,
                 logger: Any = None, service: Any = None,
                 faults: Any = None,
                 metrics: Any = None,
                 events: Any = None) -> None:
        from ..service import CircuitBreaker, Retry, new_http_service
        self.host_id = host_id
        self.leader_url = leader_url
        #: dial address advertised to the leader; a callable is
        #: re-resolved on every join/heartbeat — how ephemeral-port
        #: workers advertise an endpoint they only learn after their
        #: server binds (App.join_fleet wires this by default)
        self.address = address
        self.n_devices = n_devices
        self.heartbeat_interval_s = heartbeat_interval_s
        #: join-retry backoff ceiling (exponential from the heartbeat
        #: interval, full jitter — see start()'s run loop)
        self.join_backoff_max_s = join_backoff_max_s
        #: deterministic fault plan (serving/faults.py) for the
        #: control-plane sites heartbeat_drop / join_refused; None
        #: reads GOFR_FAULTS, unset -> the NO_FAULTS singleton
        self.faults = resolve_plan(faults)
        self.on_assignment = on_assignment
        self.health_source = health_source or (lambda: {"status": "UP"})
        #: flight-recorder digest attached to every heartbeat (None =
        #: no summary); wire with engine_fleet_sources(engine)
        self.summary_source = summary_source
        #: this host's EventLedger (the engine's, via App.join_fleet):
        #: worker-side failover/fence decisions are recorded on it and
        #: its digest piggybacks on every heartbeat so the leader can
        #: merge the fleet timeline
        from .events import NO_EVENTS as _no_events
        self.events = events if events is not None else _no_events
        #: Manager.snapshot() attached when FleetConfig.federation
        self.metrics_source = metrics_source
        self.fleet = fleet if fleet is not None else FleetConfig()
        self.tracer = tracer
        self.logger = logger
        self._service_injected = service is not None
        self._service = service if service is not None else \
            new_http_service(leader_url, Retry(max_retries=2),
                             CircuitBreaker(threshold=5, interval_s=2.0),
                             logger=logger, tracer=tracer)
        #: worker-side metrics manager (App.join_fleet wires the
        #: container's) — app_fleet_failovers rides it
        self.metrics = metrics
        if metrics is not None:
            ControlPlaneLeader._register_metrics(metrics)
        #: ranked leader candidates for the HA discovery walk; a
        #: single-URL tuple (no failover machinery) when unset
        self.candidates: tuple = \
            tuple(self.fleet.leader_candidates) or (leader_url,)
        self.missed_acks_before_failover = max(
            1, int(self.fleet.missed_acks_before_failover))
        #: highest leader epoch this worker has observed; sent on
        #: every control message, and acks carrying a LOWER epoch are
        #: rejected (worker-side fencing of revived stale leaders)
        self.epoch = 0
        self._missed_acks = 0
        self._electing = False  # reentrancy guard on the walk
        #: failover rounds by reason (tests + debug surfaces)
        self.failovers: dict[str, int] = {}
        self.assignment: ShardAssignment | None = None
        self._running = False
        self._leaving = False  # deregistered: suppress auto-rejoin
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------- wire
    def _post(self, path: str, body: dict) -> dict:
        import asyncio
        # the heartbeat thread is sync; the service client (circuit
        # breaker, retry, tracing) is async — one loop per call is
        # cheap at heartbeat cadence. The control.* span makes the RPC
        # the root of a cross-host trace: the service client injects
        # its traceparent, the leader's middleware continues it.
        span = None
        if self.tracer is not None:
            span = self.tracer.start_span(
                "control." + path.rstrip("/").rsplit("/", 1)[-1],
                attributes={"host_id": self.host_id})
        try:
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                response = asyncio.run(
                    self._service.post(path, json=body))
            else:
                # called from inside a running loop (an app on_start
                # hook): hop to a throwaway thread, carrying the
                # context so the span still rides as traceparent
                import concurrent.futures
                import contextvars
                ctx = contextvars.copy_context()
                pool = concurrent.futures.ThreadPoolExecutor(1)
                try:
                    response = pool.submit(
                        ctx.run, asyncio.run,
                        self._service.post(path, json=body)).result(30)
                finally:
                    pool.shutdown(wait=False)
        except Exception:
            if span is not None:
                span.set_status("ERROR: rpc failed")
            raise
        finally:
            if span is not None:
                span.end()
        if response.status >= 400:
            code, details = _typed_reject(response)
            if response.status == 409:
                if code == "stale_leader":
                    # the dialed leader is FENCED: it saw our higher
                    # epoch and demoted — re-discover, don't rejoin it
                    return {"stale_leader": True,
                            "leader_epoch": int(details.get("epoch", -1))}
                return {"rejoin": True}
            if code in ("not_leader", "leader_takeover"):
                return {"not_leader": True,
                        "leader_epoch": int(details.get("epoch", -1))}
            raise RuntimeError(
                f"control plane {path} -> {response.status}")
        data = response.json()
        return data.get("data", data)

    def _apply(self, payload: dict) -> None:
        raw = payload.get("assignment")
        if raw is None:
            return
        new = ShardAssignment(
            host_id=raw["host_id"], rank=int(raw["rank"]),
            world_size=int(raw["world_size"]),
            n_devices=int(raw["n_devices"]),
            generation=int(raw["generation"]),
            coordinator=raw.get("coordinator", ""))
        old = self.assignment
        self.assignment = new
        # the fleet context every span attribute set and log record
        # inherits from here on — set at join and on every re-rank
        set_fleet_context(host_id=self.host_id, rank=new.rank,
                          generation=new.generation)
        if (old is None or old.generation != new.generation) \
                and self.on_assignment is not None:
            self.on_assignment(new)

    def _healthy(self) -> bool:
        try:
            return self.health_source().get("status", "UP") == "UP"
        except Exception:
            return True  # a broken probe must not strand the agent

    def advertised_address(self) -> str:
        addr = self.address
        if callable(addr):
            try:
                addr = addr()
            except Exception:
                return ""
        return str(addr or "")

    def join(self, takeover: bool = False) -> ShardAssignment:
        if self.faults is not NO_FAULTS \
                and self.faults.trip("join_refused"):
            # injected leader refusal: exercises the join-retry backoff
            raise RuntimeError("control-plane join refused (injected)")
        body: dict[str, Any] = {
            "host_id": self.host_id,
            "address": self.advertised_address(),
            "n_devices": self.n_devices,
            "health": self.health_source(),
            "epoch": self.epoch}
        if takeover:
            body["takeover"] = True
        payload = self._post("/control/join", body)
        if payload.get("not_leader") or payload.get("stale_leader"):
            raise RuntimeError(
                "control-plane join refused: not the active leader")
        if not self._adopt_epoch(payload):
            raise RuntimeError(
                "control-plane join answered with a stale epoch")
        self._apply(payload)
        assert self.assignment is not None
        return self.assignment

    # ------------------------------------------------- leader discovery
    def _adopt_epoch(self, payload: dict) -> bool:
        """Worker-side epoch fencing: adopt the leader's epoch from an
        ack, or reject the ack when it carries a LOWER epoch than this
        worker has already seen — a revived stale leader, or an
        injected ``stale_epoch_replay``. Counts only, no clocks."""
        raw = payload.get("epoch")
        if raw is None:
            return True  # pre-HA leader: no epochs on the wire
        epoch = int(raw)
        if epoch < self.epoch:
            self.events.emit("fleet.fence_reject", severity="warn",
                             cause="stale_ack", epoch=self.epoch,
                             ack_epoch=epoch)
            return False
        if epoch > self.epoch:
            self.events.emit("fleet.epoch_bump", epoch=epoch,
                             cause="ack_adopted")
        self.epoch = epoch
        return True

    def _note_missed_ack(self) -> None:
        self._missed_acks += 1
        if self._missed_acks >= self.missed_acks_before_failover \
                and len(self.candidates) > 1:
            self._failover("missed_acks")

    def _failover(self, reason: str) -> bool:
        """One failover round: count it (``app_fleet_failovers``),
        then run the discovery walk. Reentrancy-guarded — the
        immediate post-join heartbeat inside the walk must not
        recurse into another round."""
        if self._electing:
            return False
        self._missed_acks = 0
        self.failovers[reason] = self.failovers.get(reason, 0) + 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_fleet_failovers",
                                           reason=reason)
        if self.logger:
            self.logger.warn("leader failover triggered", reason=reason,
                             host=self.host_id, epoch=self.epoch)
        self.events.emit("fleet.failover", severity="warn",
                         cause=reason, epoch=self.epoch)
        return self._locate_leader()

    def _probe_candidates(self) -> list[dict]:
        """``GET /control/leader`` on every candidate, in rank order.
        Unreachable candidates are simply absent from the result."""
        from ..service.client import probe_leader
        probes = []
        for rank, url in enumerate(self.candidates):
            info = probe_leader(
                url, timeout_s=max(1.0, self.heartbeat_interval_s))
            if info is None:
                continue
            probes.append({"rank": rank, "url": url,
                           "active": bool(info.get("active")),
                           "epoch": int(info.get("epoch", -1))})
        return probes

    @staticmethod
    def _choose_candidate(probes: list, known_epoch: int):
        """THE election decision — a pure function of the probe
        results and the worker's known epoch (TestElectionContract
        pins that it reads no clock and no RNG). Prefer the live
        active candidate with the highest epoch at or above what we
        know (ties break to the lowest rank; an active candidate
        BELOW the known epoch is a revived stale leader and is never
        adopted); with no acceptable active candidate, elect the
        lowest-ranked live one via a takeover join. Returns
        ``(url, takeover)`` or None when nothing is reachable."""
        active = [p for p in probes
                  if p["active"] and p["epoch"] >= known_epoch]
        if active:
            best = min(active, key=lambda p: (-p["epoch"], p["rank"]))
            return best["url"], False
        if probes:
            lowest = min(probes, key=lambda p: p["rank"])
            return lowest["url"], True
        return None

    def _redial(self, url: str) -> None:
        if self._service_injected:
            return  # tests inject a transport; keep it
        if url.rstrip("/") == self.leader_url.rstrip("/"):
            return
        from ..service import CircuitBreaker, Retry, new_http_service
        self.leader_url = url
        self._service = new_http_service(
            url, Retry(max_retries=2),
            CircuitBreaker(threshold=5, interval_s=2.0),
            logger=self.logger, tracer=self.tracer)

    def _locate_leader(self) -> bool:
        """Discovery walk + (re)join: probe the ranked candidates,
        elect deterministically, re-dial and join, then heartbeat
        immediately so the winner rebuilds its membership/digest/
        routing state from this worker NOW instead of one interval
        later (the stateless-rebuild takeover). Reentrancy-guarded:
        the immediate post-join heartbeat must not recurse into
        another walk."""
        if self._electing:
            return False
        self._electing = True
        try:
            probes = self._probe_candidates()
            while probes:
                choice = self._choose_candidate(probes, self.epoch)
                assert choice is not None  # probes is non-empty
                url, takeover = choice
                self._redial(url)
                try:
                    self.join(takeover=takeover)
                except Exception as exc:
                    # refused (asymmetric partition, injected refusal,
                    # raced a shutdown): strike THIS candidate and
                    # re-elect among the rest — deterministic, the
                    # probe list only shrinks
                    if self.logger:
                        self.logger.warn(
                            f"failover join to {url} failed: {exc}")
                    probes = [p for p in probes if p["url"] != url]
                    continue
                if self.logger:
                    self.logger.info(
                        "failed over to new leader", url=url,
                        epoch=self.epoch, takeover=takeover,
                        host=self.host_id)
                self._heartbeat_once()
                return True
            return False
        finally:
            self._electing = False

    def heartbeat_sync(self) -> tuple[ShardAssignment | None, bool]:
        """One synchronous heartbeat; returns (assignment, changed).
        The polling hand-off point for hosts that gate their SPMD
        launch on the group reaching a target size."""
        before = (self.assignment.generation
                  if self.assignment is not None else -1)
        self._heartbeat_once()
        after = (self.assignment.generation
                 if self.assignment is not None else -1)
        return self.assignment, after != before

    def _heartbeat_once(self) -> None:
        if self._leaving:
            return  # departing: the leave walk owns the wire now
        if self.faults is not NO_FAULTS \
                and self.faults.trip("heartbeat_drop"):
            return  # injected lossy control network: skip this beat
        generation = (self.assignment.generation
                      if self.assignment is not None else -1)
        body: dict[str, Any] = {
            "host_id": self.host_id, "generation": generation,
            "health": self.health_source(), "epoch": self.epoch}
        addr = self.advertised_address()
        if addr:
            body["address"] = addr
        if self.summary_source is not None:
            try:
                body["summary"] = self.summary_source()
            except Exception:
                pass  # a broken digest must not kill the heartbeat
        if self.events.enabled:
            try:
                body["events"] = self.events.digest()
            except Exception:
                pass  # same contract as the summary digest
        if self.fleet.federation and self.metrics_source is not None:
            try:
                snap = self.metrics_source()
            except Exception:
                snap = None
            if snap is not None:
                body["metrics"] = snap
        try:
            payload = self._post("/control/heartbeat", body)
        except Exception as exc:
            # leader unreachable: the circuit breaker is already
            # backing off — keep the last assignment and keep serving,
            # but COUNT the miss: enough of them in a row triggers the
            # failover walk (multi-candidate fleets only)
            if self.logger:
                self.logger.warn(f"control-plane heartbeat failed: {exc}")
            self._note_missed_ack()
            return
        if self.faults is not NO_FAULTS \
                and self.faults.trip("ack_drop"):
            # injected one-way loss: the leader saw the beat, the
            # worker never hears the ack — counts as a miss here
            self._note_missed_ack()
            return
        if payload.get("stale_leader") or not self._adopt_epoch(payload):
            # the dialed leader is behind our epoch (revived stale
            # leader, or an injected stale_epoch_replay): typed
            # evidence of staleness — fail over immediately, no
            # missed-ack budget needed
            self._failover("stale_leader")
            return
        if payload.get("not_leader"):
            # a standby answered: it told us so with a typed 503 —
            # re-discover the active leader immediately
            self._failover("not_leader")
            return
        self._missed_acks = 0
        if payload.get("evicted"):
            # the leader acted on our DEGRADED gossip: drop the
            # assignment and do NOT auto-rejoin until health clears
            # (the run loop gates the rejoin on health_source) — a
            # wedged host thrashing join/evict helps nobody
            self.assignment = None
            if self.logger:
                self.logger.warn(
                    "evicted by leader on degraded health; will "
                    "rejoin when healthy", host=self.host_id)
            return
        if payload.get("rejoin") and not self._leaving:
            # never re-adopt a departing worker from a stale heartbeat
            # racing its own /control/leave
            try:
                self.join()
            except Exception as exc:
                if self.logger:
                    self.logger.warn(f"rejoin failed: {exc}")
            return
        self._apply(payload)

    def start(self) -> None:
        """Begin joining + heartbeating. A leader that is not up yet
        must not be fatal (rolling restarts bring workers up first):
        the thread keeps retrying the join with backoff until it
        lands, then heartbeats."""
        self._running = True
        self._leaving = False
        try:
            self.join()
        except Exception as exc:
            if self.logger:
                self.logger.warn(
                    f"control-plane join failed, will retry: {exc}")

        def run() -> None:
            # Unassigned (leader down, evicted, join refused): retries
            # back off exponentially from the heartbeat interval up to
            # join_backoff_max_s, with FULL jitter (x0.5-1.5) — a
            # restarting leader must not be met by every worker's join
            # landing on the same heartbeat tick (thundering herd). A
            # successful join — or simply being assigned — resets the
            # backoff; assigned heartbeats keep the fixed cadence.
            base = max(0.01, self.heartbeat_interval_s)
            backoff = base
            while self._running:
                if self.assignment is not None:
                    delay = base
                else:
                    delay = backoff * (0.5 + random.random())
                time.sleep(delay)
                if not self._running:
                    return
                if self.assignment is None:
                    if self._leaving:
                        continue  # deregistered: awaiting stop()
                    if not self._healthy():
                        continue  # evicted-degraded: heal first
                    try:
                        if len(self.candidates) > 1:
                            # HA fleet: discovery walk instead of a
                            # blind re-dial of a possibly-dead leader
                            if not self._locate_leader():
                                raise RuntimeError(
                                    "no live leader candidate")
                        else:
                            self.join()
                        backoff = base
                    except Exception as exc:
                        backoff = min(backoff * 2.0,
                                      self.join_backoff_max_s)
                        if self.logger:
                            self.logger.warn(
                                f"join retry failed: {exc}; next "
                                f"attempt in <= {backoff * 1.5:.1f}s")
                else:
                    backoff = base
                    self._heartbeat_once()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"worker-{self.host_id}")
        self._thread.start()

    def deregister(self, rounds: int | None = None) -> bool:
        """Graceful leave (the SIGTERM drain path): tell the leader
        this host is going away NOW — survivors re-rank immediately
        instead of waiting out heartbeat silence. Best-effort: a dead
        leader must never block shutdown. Clears the assignment so the
        heartbeat thread does not immediately rejoin.

        In a multi-candidate fleet the leave survives a takeover
        window: when the dialed leader is down or answers with a
        typed ``not_leader``/``stale_leader`` reject, the agent
        re-probes the candidates and retries against whoever is
        active NOW — but never takeover-joins (a departing worker
        must not elect a leader on its way out). Returns True when a
        leader acknowledged the leave."""
        self._leaving = True
        self.assignment = None
        body = {"host_id": self.host_id, "epoch": self.epoch}
        if rounds is None:
            rounds = max(1, self.missed_acks_before_failover)
        for attempt in range(rounds):
            try:
                payload = self._post("/control/leave", body)
            except Exception as exc:
                payload = {"error": str(exc)}
            if not (payload.get("not_leader") or payload.get("stale_leader")
                    or payload.get("error")):
                self._adopt_epoch(payload)
                if self.logger:
                    self.logger.info("deregistered from serving group",
                                     host=self.host_id)
                return True
            if attempt + 1 >= rounds:
                break
            # a takeover may be mid-flight: give the election one
            # heartbeat interval, then re-discover the front door
            time.sleep(self.heartbeat_interval_s)
            if len(self.candidates) > 1:
                choice = self._choose_candidate(
                    self._probe_candidates(), self.epoch)
                if choice is not None and not choice[1]:
                    self._redial(choice[0])
        if self.logger:
            self.logger.warn("control-plane leave failed",
                             host=self.host_id)
        return False

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(self.heartbeat_interval_s * 2 + 1)
            self._thread = None
