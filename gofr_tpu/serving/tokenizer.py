"""Tokenizers for the serving path.

``ByteTokenizer`` is the dependency-free default: UTF-8 bytes + special
tokens, reversible for any text, vocab 260. Real deployments load a BPE
vocabulary via ``BPETokenizer.from_files`` (tiktoken-format); the hot
merge loop has a C++ fast path (gofr_tpu/native) with this pure-Python
fallback.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Protocol


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 are bytes, then specials."""

    def __init__(self) -> None:
        self.pad_id = 256
        self.bos_id = 257
        self.eos_id = 258
        self.unk_id = 259
        self.vocab_size = 260

    def encode(self, text: str, *, bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if bos else ids

    def decode(self, ids: list[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", "replace")


class BPETokenizer:
    """Byte-pair tokenizer over a rank table (tiktoken file format:
    ``base64(token_bytes) rank`` per line)."""

    def __init__(self, ranks: dict[bytes, int],
                 specials: dict[str, int] | None = None) -> None:
        self.ranks = ranks
        self.specials = dict(specials or {})
        base = len(ranks)
        self.bos_id = self.specials.setdefault("<|bos|>", base)
        self.eos_id = self.specials.setdefault("<|eos|>", base + 1)
        self.pad_id = self.specials.setdefault("<|pad|>", base + 2)
        self.vocab_size = base + len(self.specials)
        self._decode_table: dict[int, bytes] = {v: k for k, v in ranks.items()}
        self._native = None
        try:
            from ..native import bpe as native_bpe
            self._native = native_bpe.load(ranks)
        except Exception:
            self._native = None

    @classmethod
    def from_files(cls, ranks_path: str | Path,
                   specials_path: str | Path | None = None) -> "BPETokenizer":
        import base64
        ranks: dict[bytes, int] = {}
        for line in Path(ranks_path).read_text().splitlines():
            if not line.strip():
                continue
            token_b64, rank = line.split()
            ranks[base64.b64decode(token_b64)] = int(rank)
        specials = None
        if specials_path and Path(specials_path).is_file():
            specials = json.loads(Path(specials_path).read_text())
        return cls(ranks, specials)

    def _bpe_merge(self, piece: bytes) -> list[int]:
        """Greedy lowest-rank merging (pure-Python fallback)."""
        parts: list[bytes] = [piece[i:i + 1] for i in range(len(piece))]
        while len(parts) > 1:
            best_rank = None
            best_i = -1
            for i in range(len(parts) - 1):
                rank = self.ranks.get(parts[i] + parts[i + 1])
                if rank is not None and (best_rank is None or rank < best_rank):
                    best_rank, best_i = rank, i
            if best_rank is None:
                break
            parts[best_i:best_i + 2] = [parts[best_i] + parts[best_i + 1]]
        out = []
        for p in parts:
            rank = self.ranks.get(p)
            if rank is not None:
                out.append(rank)
            else:  # unmergeable byte without a rank: skip (lossy, rare)
                out.extend(r for r in (self.ranks.get(p[i:i+1])
                                       for i in range(len(p))) if r is not None)
        return out

    def encode(self, text: str, *, bos: bool = True) -> list[int]:
        data = text.encode("utf-8")
        if self._native is not None:
            ids = self._native.encode(data)
        else:
            ids = self._bpe_merge(data)
        return ([self.bos_id] + ids) if bos else ids

    def decode(self, ids: list[int]) -> str:
        chunks = [self._decode_table.get(i, b"") for i in ids]
        return b"".join(chunks).decode("utf-8", "replace")
