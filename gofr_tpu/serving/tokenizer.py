"""Tokenizers for the serving path.

``ByteTokenizer`` is the dependency-free default: UTF-8 bytes + special
tokens, reversible for any text, vocab 260. Real deployments load a BPE
vocabulary via ``BPETokenizer.from_files`` (tiktoken-format); the hot
merge loop has a C++ fast path (gofr_tpu/native) with this pure-Python
fallback.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Protocol


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 are bytes, then specials."""

    def __init__(self) -> None:
        self.pad_id = 256
        self.bos_id = 257
        self.eos_id = 258
        self.unk_id = 259
        self.vocab_size = 260

    def encode(self, text: str, *, bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if bos else ids

    def decode(self, ids: list[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", "replace")


def _byte_level_table() -> dict[str, int]:
    """The GPT-2 byte<->printable-unicode bijection HF byte-level BPE
    vocabularies are written in: printable ASCII and two latin-1
    ranges map to themselves, everything else shifts into U+0100+."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


#: Llama-3 / GPT-4 style pre-tokenizer, approximated for the stdlib
#: ``re`` engine: ``\p{L}`` becomes ``[^\W\d_]`` and ``\p{N}`` becomes
#: ``\d`` (exotic unicode-numeral edge cases may split differently
#: than HF's regex; byte-level BPE keeps the result lossless either
#: way).
_PRETOKENIZE = re.compile(
    r"'(?i:[sdmt]|ll|ve|re)"
    r"|(?:(?![\r\n])[\W_])?[^\W\d_]+"
    r"|\d{1,3}"
    r"| ?(?:(?!\s)[\W_])+[\r\n]*"
    r"|\s*[\r\n]+"
    r"|\s+(?!\S)"
    r"|\s+")


class BPETokenizer:
    """Byte-pair tokenizer over a rank table (tiktoken file format:
    ``base64(token_bytes) rank`` per line), or a Hugging Face
    ``tokenizer.json`` via :meth:`from_hf_json`.

    In tiktoken form the vocabulary id IS the merge priority. HF
    vocabularies separate the two (merge order comes from the
    ``merges`` list), so ``merge_ranks`` can override the priorities
    the merge loop uses while ``ranks`` keeps mapping final pieces to
    ids."""

    def __init__(self, ranks: dict[bytes, int],
                 specials: dict[str, int] | None = None, *,
                 merge_ranks: dict[bytes, int] | None = None,
                 pretokenize: bool = False,
                 bos_token: str | None = None,
                 eos_token: str | None = None,
                 pad_token: str | None = None) -> None:
        self.ranks = ranks
        self.specials = dict(specials or {})
        self.merge_ranks = merge_ranks
        self._pretok = _PRETOKENIZE if pretokenize else None

        def special(name: str | None, default: str, fallback: int) -> int:
            if name is not None:
                return self.specials[name]
            return self.specials.setdefault(default, fallback)

        base = max(max(ranks.values(), default=-1) + 1,
                   max(self.specials.values(), default=-1) + 1)
        self.bos_id = special(bos_token, "<|bos|>", base)
        self.eos_id = special(eos_token, "<|eos|>", base + 1)
        self.pad_id = special(pad_token, "<|pad|>", base + 2)
        self.vocab_size = max(
            (max(ranks.values(), default=-1),
             max(self.specials.values(), default=-1))) + 1
        self._decode_table: dict[int, bytes] = {v: k for k, v in ranks.items()}
        for text, sid in self.specials.items():
            self._decode_table.setdefault(sid, text.encode())
        self._native = None
        try:
            from ..native import bpe as native_bpe
            self._native = native_bpe.load(ranks, merge_ranks)
        except Exception:
            self._native = None

    @classmethod
    def from_files(cls, ranks_path: str | Path,
                   specials_path: str | Path | None = None) -> "BPETokenizer":
        import base64
        ranks: dict[bytes, int] = {}
        for line in Path(ranks_path).read_text().splitlines():
            if not line.strip():
                continue
            token_b64, rank = line.split()
            ranks[base64.b64decode(token_b64)] = int(rank)
        specials = None
        if specials_path and Path(specials_path).is_file():
            specials = json.loads(Path(specials_path).read_text())
        return cls(ranks, specials)

    @classmethod
    def from_hf_json(cls, path: str | Path, *,
                     bos_token: str | None = None,
                     eos_token: str | None = None) -> "BPETokenizer":
        """Ingest a Hugging Face ``tokenizer.json`` (byte-level BPE —
        the Llama-3 / GPT-2 family layout): the ``model.vocab`` token
        strings decode through the byte-level table back to raw
        bytes, merge priority comes from the ``merges`` list, and
        ``added_tokens`` become specials. ``bos_token``/``eos_token``
        default to the usual Llama-3 names when present."""
        spec = json.loads(Path(path).read_text())
        table = _byte_level_table()

        def to_bytes(token: str) -> bytes:
            return bytes(table[ch] for ch in token if ch in table)

        vocab = spec["model"]["vocab"]
        ranks: dict[bytes, int] = {}
        for token, idx in vocab.items():
            b = to_bytes(token)
            if len(b) == len(token):  # pure byte-level entry
                ranks[b] = idx
        merges = spec["model"].get("merges", [])
        merge_ranks: dict[bytes, int] = {}
        for m, pair in enumerate(merges):
            left, right = pair.split(" ") if isinstance(pair, str) else pair
            merge_ranks[to_bytes(left) + to_bytes(right)] = m
        specials = {t["content"]: t["id"]
                    for t in spec.get("added_tokens", [])}
        if bos_token is None and "<|begin_of_text|>" in specials:
            bos_token = "<|begin_of_text|>"
        if eos_token is None and "<|end_of_text|>" in specials:
            eos_token = "<|end_of_text|>"
        return cls(ranks, specials, merge_ranks=merge_ranks or None,
                   pretokenize=True, bos_token=bos_token,
                   eos_token=eos_token)

    def _bpe_merge(self, piece: bytes) -> list[int]:
        """Greedy lowest-rank merging (pure-Python fallback)."""
        priorities = self.merge_ranks if self.merge_ranks is not None \
            else self.ranks
        parts: list[bytes] = [piece[i:i + 1] for i in range(len(piece))]
        while len(parts) > 1:
            best_rank = None
            best_i = -1
            for i in range(len(parts) - 1):
                rank = priorities.get(parts[i] + parts[i + 1])
                if rank is not None and (best_rank is None or rank < best_rank):
                    best_rank, best_i = rank, i
            if best_rank is None:
                break
            parts[best_i:best_i + 2] = [parts[best_i] + parts[best_i + 1]]
        out = []
        for p in parts:
            rank = self.ranks.get(p)
            if rank is not None:
                out.append(rank)
            else:  # unmergeable byte without a rank: skip (lossy, rare)
                out.extend(r for r in (self.ranks.get(p[i:i+1])
                                       for i in range(len(p))) if r is not None)
        return out

    def encode(self, text: str, *, bos: bool = True) -> list[int]:
        if self._pretok is not None:
            str_pieces = self._pretok.findall(text)
            pieces = [p.encode("utf-8") for p in str_pieces]
            # the pattern tiles any input, but guard anyway (by char
            # count — findall returns ordered substrings, so full
            # coverage implies equality): a gap would make whole-text
            # native encoding see bytes the per-piece fallback drops
            if self._native is not None \
                    and sum(map(len, str_pieces)) == len(text):
                # ONE GIL-released native call for the whole text:
                # piece boundaries ride along as byte offsets merges
                # may not cross
                bounds: list[int] = []
                off = 0
                for piece in pieces:
                    bounds.append(off)
                    off += len(piece)
                ids = self._native.encode(b"".join(pieces), bounds)
            else:
                ids = []
                for piece in pieces:
                    ids.extend(self._bpe_merge(piece))
        elif self._native is not None:
            ids = self._native.encode(text.encode("utf-8"))
        else:
            ids = self._bpe_merge(text.encode("utf-8"))
        return ([self.bos_id] + ids) if bos else ids

    def decode(self, ids: list[int]) -> str:
        chunks = [self._decode_table.get(i, b"") for i in ids]
        return b"".join(chunks).decode("utf-8", "replace")
