"""The fleet flight data recorder: a causal event ledger.

Every *action* the serving stack takes — admission rejects,
preemptions, shed episodes, engine restarts, fault trips, evictions,
failovers, fence rejects, route retries, recompiles, watermark
crossings — was until now announced only as a WARN-once log line plus
a counter. This module records each one as a structured event so an
operator (or ``scripts/bundle.py``) can reconstruct "what happened, in
what order, on which host, to whose requests" from one artifact:

``{ts, host, kind, severity, request_id?, tenant?, trace_id?, epoch?,
cause?, attrs}``

Design rules (the zero-hot-path-perturbation invariant, PR 3):

- The ring is **fixed**: ``EventLedgerConfig.capacity`` events, after
  which the oldest rotates out and is counted in a per-kind drop
  counter — a truncated history is visible, never silent.
- :meth:`EventLedger.emit` is a ``@hot_path_boundary``: emission only
  happens at sites that already declared a boundary (scheduler
  admission, preemption, crash recovery, fault trips, control-plane
  transitions) — never from decode/prefill dispatch or collect inner
  loops. gofrlint pins this (``tests/analysis_fixtures/events_*``).
- The disabled ledger is the :data:`NO_EVENTS` singleton (capacity 0);
  ``emit`` returns before taking the lock, so OFF costs one attribute
  read and an integer compare.

Serialization follows the ``gofr-workload`` contract exactly: JSONL
with a one-line header ``{"format": "gofr-events", "version": 1}``;
readers refuse unknown formats/versions (:func:`parse_events`).

Fleet federation rides the existing heartbeat: each worker piggybacks
:meth:`EventLedger.digest` (its newest events + its wall clock ``now``)
on the control-plane heartbeat body, and the leader's
:class:`FleetEventMerger` folds them into one skew-corrected timeline
— per-host clock offset is estimated as ``leader_receive_wall - now``
(the same digest-on-heartbeat channel the PR 4 skew detector uses), so
cross-host ordering survives unsynchronized clocks; epochs break ties
across failovers. Served at ``GET /debug/fleet/events``.

:class:`IncidentDetector` turns three conditions — an SLO fast-burn
trip, a committed leader failover, a crash-restart budget overrun —
into a **bundle**: merged event timeline around the trigger, flight
recorder dump, goodput/SLO/scheduler/watermark state, config + git
digest, spooled to a bounded in-memory ring (optionally mirrored to a
bounded on-disk spool) and served at ``GET /debug/incidents``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import Counter, OrderedDict
from dataclasses import dataclass

from ..analysis.annotations import hot_path_boundary

#: header contract, mirroring WORKLOAD_FORMAT/WORKLOAD_VERSION
EVENTS_FORMAT = "gofr-events"
EVENTS_VERSION = 1

SEVERITIES = ("info", "warn", "error")

#: the kind catalog (docs/observability.md). Emitting an unknown kind
#: raises — a typo'd kind silently fragmenting the timeline would make
#: every ``?kind=`` query and replay diff quietly wrong.
KINDS = frozenset({
    # scheduler.py — admission and overload actions
    "sched.reject", "sched.preempt", "sched.shed_open",
    "sched.shed_close",
    # engine.py — lifecycle transitions
    "engine.restart", "engine.recovery", "engine.crash",
    "engine.drain", "engine.stranded_slot",
    # faults.py — injected failures firing
    "fault.trip",
    # control_plane.py — fleet membership and leadership
    "fleet.evict", "fleet.straggler", "fleet.stall",
    "fleet.failover", "fleet.epoch_bump", "fleet.fence_reject",
    # router.py — front-door actions
    "router.retry", "router.failover", "router.affinity_drop",
    "router.scale",
    # observability.py / costmodel.py — efficiency sentinels
    "obs.recompile", "obs.watermark", "obs.fast_burn",
    "obs.cost_drift",
    # integrity.py — output-integrity observatory: golden-probe
    # results/mismatch episodes (engine side) and the leader's
    # divergence-vote verdicts + quarantine/rejoin actions
    "obs.integrity", "fleet.integrity_divergence", "fleet.quarantine",
    # events.py itself — an incident bundle was spooled
    "incident.open",
})


@dataclass
class EventLedgerConfig:
    """Knobs for the ledger and the incident spool (docs/configs.md)."""

    #: fixed ring bound; beyond it the oldest event rotates out and is
    #: counted in the per-kind drop counter. 0 disables the ledger.
    capacity: int = 4096
    #: newest events piggybacked on each heartbeat digest — the fleet
    #: federation budget (small on purpose: the gRPC micro-benchmark
    #: literature says small-payload RPC overhead dominates)
    digest_size: int = 32
    #: incident bundles capture the merged timeline this far around
    #: the trigger (seconds)
    incident_window_s: float = 60.0
    #: one bundle per reason per this many seconds — a flapping
    #: condition must not fill the spool with near-identical bundles
    incident_debounce_s: float = 30.0
    #: bounded bundle count kept in memory (and on disk when
    #: ``spool_dir`` is set); the oldest bundle is pruned beyond it
    spool_max: int = 8
    #: optional on-disk mirror for bundles (``GOFR_INCIDENT_DIR``);
    #: None keeps the spool memory-only
    spool_dir: str | None = None


class EventLedger:
    """Bounded, thread-safe ring of structured events.

    ``emit`` runs on whichever thread owns the transition (submitter
    threads for admission, the engine thread for recovery, heartbeat
    threads for fleet changes) — all host-side, never device code."""

    def __init__(self, config: EventLedgerConfig | None = None, *,
                 host: str = "", metrics=None,
                 clock=time.time) -> None:
        self.config = config if config is not None else EventLedgerConfig()
        self.host = host
        self.metrics = metrics
        self.clock = clock
        self._capacity = max(0, int(self.config.capacity))
        self._lock = threading.Lock()
        self._ring: OrderedDict[int, dict] = OrderedDict()
        self._seq = 0
        #: per-kind counts of events rotated out of the ring
        self.dropped: dict[str, int] = {}
        #: per-kind lifetime emission counts
        self.totals: dict[str, int] = {}

    # ------------------------------------------------------------ state
    @property
    def enabled(self) -> bool:
        return self._capacity > 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------- emit
    @hot_path_boundary(
        "event emission: only invoked from sites that already declared "
        "a boundary (admission, preemption, recovery, fault trips, "
        "fleet transitions) — the dict build and ring rotation here are "
        "host-side; the disabled NO_EVENTS singleton returns before the "
        "lock")
    def emit(self, kind: str, *, severity: str = "info",
             request_id=None, tenant=None, trace_id=None, epoch=None,
             cause=None, t: float | None = None, **attrs):
        """Record one event; returns the record, or None when disabled.

        Unknown kinds and severities raise (fail loudly — see
        :data:`KINDS`)."""
        if not self._capacity:
            return None
        if kind not in KINDS:
            raise ValueError(f"unknown event kind {kind!r}; known: "
                             f"{', '.join(sorted(KINDS))}")
        if severity not in SEVERITIES:
            raise ValueError(f"unknown severity {severity!r}; known: "
                             f"{', '.join(SEVERITIES)}")
        evicted_kind = None
        with self._lock:
            self._seq += 1
            event = {"seq": self._seq,
                     "ts": self.clock() if t is None else float(t),
                     "host": self.host, "kind": kind,
                     "severity": severity}
            if request_id is not None:
                event["request_id"] = request_id
            if tenant is not None:
                event["tenant"] = tenant
            if trace_id is not None:
                event["trace_id"] = trace_id
            if epoch is not None:
                event["epoch"] = int(epoch)
            if cause is not None:
                event["cause"] = cause
            if attrs:
                event["attrs"] = attrs
            if len(self._ring) >= self._capacity:
                _, old = self._ring.popitem(last=False)
                evicted_kind = old["kind"]
                self.dropped[evicted_kind] = \
                    self.dropped.get(evicted_kind, 0) + 1
            self._ring[self._seq] = event
            self.totals[kind] = self.totals.get(kind, 0) + 1
        m = self.metrics
        if m is not None:
            m.increment_counter("app_events_total", kind=kind)
            if evicted_kind is not None:
                m.increment_counter("app_events_dropped",
                                    kind=evicted_kind)
        return event

    # --------------------------------------------------------- snapshot
    def snapshot(self, *, kind: str | None = None,
                 since: float | None = None,
                 n: int | None = None) -> list[dict]:
        """Filtered copy of the retained events, oldest first."""
        with self._lock:
            events = list(self._ring.values())
        if kind is not None:
            events = [e for e in events if e["kind"] == kind]
        if since is not None:
            events = [e for e in events if e["ts"] >= since]
        if n is not None and n >= 0:
            events = events[-n:] if n else []
        return [dict(e) for e in events]

    def header(self) -> dict:
        """The ``gofr-events`` JSONL header line object."""
        with self._lock:
            return {"format": EVENTS_FORMAT, "version": EVENTS_VERSION,
                    "host": self.host, "seq": self._seq,
                    "retained": len(self._ring),
                    "dropped": dict(self.dropped)}

    def to_jsonl(self, *, kind: str | None = None,
                 since: float | None = None,
                 n: int | None = None) -> str:
        lines = [json.dumps(self.header(), sort_keys=True)]
        lines.extend(json.dumps(e, sort_keys=True) for e in
                     self.snapshot(kind=kind, since=since, n=n))
        return "\n".join(lines) + "\n"

    def digest(self) -> dict:
        """The heartbeat piggyback: the newest ``digest_size`` events
        plus this host's wall clock, from which the leader estimates
        the per-host clock offset."""
        size = max(0, int(self.config.digest_size))
        with self._lock:
            events = list(self._ring.values())[-size:] if size else []
            return {"now": self.clock(), "host": self.host,
                    "seq": self._seq,
                    "dropped": dict(self.dropped),
                    "events": [dict(e) for e in events]}

    def state(self) -> dict:
        """The ``GET /debug/events`` sidecar state (ring accounting)."""
        with self._lock:
            return {"enabled": self.enabled,
                    "capacity": self._capacity,
                    "retained": len(self._ring), "seq": self._seq,
                    "totals": dict(self.totals),
                    "dropped": dict(self.dropped)}


#: The disabled ledger. Wiring compares identity (``is not NO_EVENTS``)
#: where it matters; ``emit`` on it is a two-comparison no-op. Never
#: mutate it.
NO_EVENTS = EventLedger(EventLedgerConfig(capacity=0, digest_size=0))


def resolve_ledger(value, *, host: str = "", metrics=None,
                   clock=time.time) -> EventLedger:
    """Normalize an ``events`` config knob: an :class:`EventLedger` →
    itself; ``None``/``True`` → a default-capacity ledger (unless
    ``GOFR_EVENTS`` is ``0``/``false``/``off``); ``False`` →
    :data:`NO_EVENTS`; an :class:`EventLedgerConfig` → a ledger built
    from it (capacity 0 collapses to the singleton)."""
    if isinstance(value, EventLedger):
        return value
    if value is False:
        return NO_EVENTS
    if value is None or value is True:
        if os.environ.get("GOFR_EVENTS", "").strip().lower() in \
                ("0", "false", "off"):
            return NO_EVENTS
        return EventLedger(host=host, metrics=metrics, clock=clock)
    if isinstance(value, EventLedgerConfig):
        if value.capacity <= 0:
            return NO_EVENTS
        return EventLedger(value, host=host, metrics=metrics,
                           clock=clock)
    raise TypeError(f"events must be None, bool, EventLedgerConfig or "
                    f"EventLedger, got {type(value).__name__}")


# ---------------------------------------------------------------- parse
def parse_events(text: str) -> tuple[dict, list[dict]]:
    """Parse a ``gofr-events`` JSONL capture; refuses unknown formats
    and versions (same contract as ``replay.parse_workload``)."""
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError("empty events capture")
    header = json.loads(lines[0])
    if not isinstance(header, dict) or \
            header.get("format") != EVENTS_FORMAT:
        raise ValueError(
            f"not a {EVENTS_FORMAT} capture: header {lines[0][:120]!r}")
    if header.get("version") != EVENTS_VERSION:
        raise ValueError(
            f"unsupported {EVENTS_FORMAT} version "
            f"{header.get('version')!r} (this reader speaks "
            f"{EVENTS_VERSION})")
    events = [json.loads(ln) for ln in lines[1:]]
    for ev in events:
        if not isinstance(ev, dict) or "kind" not in ev or "ts" not in ev:
            raise ValueError(f"malformed event record: {ev!r}")
    return header, events


# ---------------------------------------------------------------- merge
class FleetEventMerger:
    """Leader-side accumulator for heartbeat event digests.

    Each host's digests are deduplicated by ``seq`` into a bounded
    per-host store; the per-host clock offset is re-estimated on every
    ingest as ``received_wall - digest["now"]`` (network latency rides
    inside the estimate — fine for ordering, the same tolerance the
    PR 4 skew detector accepts). :meth:`timeline` merges all hosts into
    one list ordered by ``(corrected ts, epoch, host, seq)`` — epoch
    breaking ties means a fence reject at epoch 1 sorts before the
    takeover commit at epoch 2 even under clock skew smaller than the
    heartbeat quantum."""

    def __init__(self, capacity_per_host: int = 1024,
                 clock=time.time) -> None:
        self.capacity_per_host = max(1, int(capacity_per_host))
        self.clock = clock
        self._lock = threading.Lock()
        #: host -> {"events": OrderedDict[seq, event], "offset_s": ...}
        self._hosts: dict[str, dict] = {}

    def ingest(self, host_id: str, digest: dict,
               received: float | None = None) -> None:
        if not isinstance(digest, dict):
            return
        received = self.clock() if received is None else received
        sent = digest.get("now")
        offset = (received - float(sent)) \
            if isinstance(sent, (int, float)) else 0.0
        with self._lock:
            entry = self._hosts.setdefault(
                host_id, {"events": OrderedDict(), "offset_s": 0.0,
                          "dropped": {}, "last_seen": 0.0})
            entry["offset_s"] = offset
            entry["last_seen"] = received
            entry["dropped"] = dict(digest.get("dropped") or {})
            store = entry["events"]
            for ev in digest.get("events") or ():
                if not isinstance(ev, dict) or "seq" not in ev:
                    continue
                store.setdefault(int(ev["seq"]), ev)
            while len(store) > self.capacity_per_host:
                store.popitem(last=False)

    def forget(self, host_id: str) -> None:
        with self._lock:
            self._hosts.pop(host_id, None)

    def timeline(self, *, kind: str | None = None,
                 since: float | None = None,
                 until: float | None = None,
                 n: int | None = None) -> list[dict]:
        """The merged, skew-corrected fleet timeline (oldest first).
        ``since``/``until`` filter on the corrected timestamps."""
        merged: list[dict] = []
        with self._lock:
            for host_id, entry in self._hosts.items():
                offset = entry["offset_s"]
                for ev in entry["events"].values():
                    rec = dict(ev)
                    if not rec.get("host"):
                        rec["host"] = host_id
                    rec["ts_corrected"] = round(
                        float(rec.get("ts", 0.0)) + offset, 6)
                    rec["skew_s"] = round(offset, 6)
                    merged.append(rec)
        if kind is not None:
            merged = [e for e in merged if e.get("kind") == kind]
        if since is not None:
            merged = [e for e in merged if e["ts_corrected"] >= since]
        if until is not None:
            merged = [e for e in merged if e["ts_corrected"] <= until]
        merged.sort(key=lambda e: (e["ts_corrected"],
                                   e.get("epoch") or 0,
                                   str(e.get("host") or ""),
                                   e.get("seq") or 0))
        if n is not None and n >= 0:
            merged = merged[-n:] if n else []
        return merged

    def header(self) -> dict:
        with self._lock:
            return {"format": EVENTS_FORMAT,
                    "version": EVENTS_VERSION, "merged": True,
                    "hosts": {h: {"offset_s": round(e["offset_s"], 6),
                                  "retained": len(e["events"]),
                                  "dropped": e["dropped"]}
                              for h, e in sorted(self._hosts.items())}}

    def to_jsonl(self, *, kind: str | None = None,
                 since: float | None = None,
                 n: int | None = None) -> str:
        lines = [json.dumps(self.header(), sort_keys=True)]
        lines.extend(json.dumps(e, sort_keys=True) for e in
                     self.timeline(kind=kind, since=since, n=n))
        return "\n".join(lines) + "\n"


# ------------------------------------------------------------- incidents
def _git_digest(start: str | None = None) -> dict:
    """Best-effort repo identity for bundles, read straight from
    ``.git`` (no subprocess — bundle capture must work in restricted
    runtimes). Unknown → Nones, never a guess."""
    path = start or os.path.dirname(os.path.abspath(__file__))
    for _ in range(10):
        git = os.path.join(path, ".git")
        if os.path.isdir(git):
            try:
                with open(os.path.join(git, "HEAD"),
                          encoding="utf-8") as fh:
                    head = fh.read().strip()
                if not head.startswith("ref:"):
                    return {"commit": head, "ref": None}
                ref = head.partition(":")[2].strip()
                ref_path = os.path.join(git, *ref.split("/"))
                if os.path.exists(ref_path):
                    with open(ref_path, encoding="utf-8") as fh:
                        return {"commit": fh.read().strip(), "ref": ref}
                packed = os.path.join(git, "packed-refs")
                if os.path.exists(packed):
                    with open(packed, encoding="utf-8") as fh:
                        for line in fh:
                            if line.strip().endswith(" " + ref) or \
                                    line.strip().endswith("\t" + ref):
                                return {"commit": line.split()[0],
                                        "ref": ref}
                return {"commit": None, "ref": ref}
            except OSError:
                return {"commit": None, "ref": None}
        parent = os.path.dirname(path)
        if parent == path:
            break
        path = parent
    return {"commit": None, "ref": None}


class IncidentDetector:
    """Snapshots a diagnostic bundle when the fleet does something an
    operator will be asked about: an SLO **fast_burn** trip, a
    committed leader **failover**, a crash-restart budget overrun
    (**restart_budget**), or a dispatch signature's pass cost departing
    its sealed baseline (**cost_drift** — serving/costmodel.py; the
    bundle's ``costs`` source carries the per-signature table and the
    auto-captured profiler artifact path rides the trigger attrs), a
    golden canary probe whose output fingerprint departed its sealed
    digest (**integrity** — serving/integrity.py; the bundle's
    ``integrity`` source names which golden prompt diverged), or the
    leader's divergence vote naming an outlier host
    (**integrity_divergence** — the fleet-side bundle carries the
    vote, the outlier and the quarantine action).

    The bundle is assembled from pluggable zero-arg ``sources`` (slo /
    scheduler / watermarks / goodput / recorder / config blocks — a
    broken source contributes its error string, never aborts the
    capture) plus the event timeline around the trigger. Bundles open
    with the pre-trigger half of the window and are **sealed** with the
    post-trigger half on the first read after ``ts + window`` — the
    3am page links to a bundle that, by the time a human opens it,
    covers both sides of the incident."""

    REASONS = ("fast_burn", "failover", "restart_budget", "cost_drift",
               "integrity", "integrity_divergence")

    def __init__(self, config: EventLedgerConfig | None = None, *,
                 ledger: EventLedger | None = None, host: str = "",
                 logger=None, clock=time.time) -> None:
        self.config = config if config is not None else EventLedgerConfig()
        self.ledger = ledger if ledger is not None else NO_EVENTS
        self.host = host
        self.logger = logger
        self.clock = clock
        #: name -> zero-arg callable returning a JSON-able state block
        self.sources: dict = {}
        #: optional callable(since, until) -> merged fleet timeline;
        #: None falls back to the local ledger snapshot
        self.timeline_source = None
        self._lock = threading.Lock()
        self._last: dict[str, float] = {}
        self._bundles: OrderedDict[str, dict] = OrderedDict()
        self._count = 0
        self.debounced: dict[str, int] = {}
        if self.config.spool_dir:
            try:
                os.makedirs(self.config.spool_dir, exist_ok=True)
            except OSError:
                pass

    # ---------------------------------------------------------- trigger
    def trigger(self, reason: str, *, cause: str | None = None,
                trace_id: str | None = None, epoch=None,
                attrs: dict | None = None) -> dict | None:
        """Open one incident bundle; returns its metadata, or None when
        the per-reason debounce suppressed it."""
        if reason not in self.REASONS:
            raise ValueError(f"unknown incident reason {reason!r}; "
                             f"known: {', '.join(self.REASONS)}")
        now = self.clock()
        with self._lock:
            last = self._last.get(reason)
            if last is not None and \
                    now - last < self.config.incident_debounce_s:
                self.debounced[reason] = \
                    self.debounced.get(reason, 0) + 1
                return None
            self._last[reason] = now
            self._count += 1
            incident_id = f"{self.host or 'local'}-{self._count:04d}-" \
                          f"{reason}"
        bundle = self._capture(incident_id, reason, now, cause,
                               trace_id, epoch, attrs)
        with self._lock:
            self._bundles[incident_id] = bundle
            evicted = []
            while len(self._bundles) > max(1, self.config.spool_max):
                old_id, _ = self._bundles.popitem(last=False)
                evicted.append(old_id)
        self._spool(bundle)
        for old_id in evicted:
            self._unspool(old_id)
        self.ledger.emit("incident.open", severity="error",
                         cause=reason, trace_id=trace_id, epoch=epoch,
                         incident_id=incident_id)
        if self.logger is not None:
            self.logger.warn(
                f"incident bundle {incident_id} opened: {reason}"
                + (f" ({cause})" if cause else ""),
                incident_id=incident_id, reason=reason)
        return self._meta(bundle)

    def _capture(self, incident_id, reason, now, cause, trace_id,
                 epoch, attrs) -> dict:
        window = max(0.0, float(self.config.incident_window_s))
        state = {}
        for name, source in sorted(self.sources.items()):
            try:
                state[name] = source()
            except Exception as exc:  # a broken source must not
                state[name] = {"error": f"{type(exc).__name__}: {exc}"}
        bundle = {
            "format": "gofr-incident", "version": 1,
            "id": incident_id, "ts": now, "host": self.host,
            "reason": reason, "cause": cause, "trace_id": trace_id,
            "epoch": epoch, "attrs": attrs or {},
            "window_s": window, "sealed": window == 0.0,
            "timeline": self._timeline(now - window, now),
            "state": state, "git": _git_digest(),
            "ledger": self.ledger.state(),
        }
        return bundle

    def _timeline(self, since, until) -> list[dict]:
        source = self.timeline_source
        if source is not None:
            try:
                return source(since, until)
            except Exception:
                pass  # fall through to the local view
        return [e for e in self.ledger.snapshot(since=since)
                if float(e.get("ts", 0.0)) <= until]

    def _seal_locked(self, bundle: dict) -> None:
        """Top up the post-trigger half of the timeline on read; mark
        sealed once the window has fully elapsed."""
        if bundle.get("sealed"):
            return
        now = self.clock()
        until = min(now, bundle["ts"] + bundle["window_s"])
        tail = [e for e in self._timeline(bundle["ts"], until)
                if (e.get("seq"), e.get("host")) not in
                {(x.get("seq"), x.get("host"))
                 for x in bundle["timeline"]}]
        bundle["timeline"] = bundle["timeline"] + tail
        if now >= bundle["ts"] + bundle["window_s"]:
            bundle["sealed"] = True
        self._spool(bundle)

    # ------------------------------------------------------------ spool
    def _path(self, incident_id: str) -> str | None:
        if not self.config.spool_dir:
            return None
        return os.path.join(self.config.spool_dir,
                            f"incident-{incident_id}.json")

    def _spool(self, bundle: dict) -> None:
        path = self._path(bundle["id"])
        if path is None:
            return
        try:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(bundle, fh, sort_keys=True, default=str)
            os.replace(tmp, path)
        except OSError:
            pass  # the in-memory spool is the source of truth

    def _unspool(self, incident_id: str) -> None:
        path = self._path(incident_id)
        if path is None:
            return
        try:
            os.remove(path)
        except OSError:
            pass

    # ------------------------------------------------------------- read
    @staticmethod
    def _meta(bundle: dict) -> dict:
        return {"id": bundle["id"], "ts": bundle["ts"],
                "host": bundle["host"], "reason": bundle["reason"],
                "cause": bundle["cause"],
                "trace_id": bundle["trace_id"],
                "sealed": bundle["sealed"],
                "events": len(bundle["timeline"])}

    def list(self) -> list[dict]:
        """Newest-last metadata for ``GET /debug/incidents``."""
        with self._lock:
            for bundle in self._bundles.values():
                self._seal_locked(bundle)
            return [self._meta(b) for b in self._bundles.values()]

    def get(self, incident_id: str) -> dict | None:
        with self._lock:
            bundle = self._bundles.get(incident_id)
            if bundle is None:
                return None
            self._seal_locked(bundle)
            return json.loads(json.dumps(bundle, default=str))

    def state(self) -> dict:
        with self._lock:
            return {"spooled": len(self._bundles),
                    "spool_max": self.config.spool_max,
                    "spool_dir": self.config.spool_dir,
                    "debounced": dict(self.debounced),
                    "last_trigger": dict(self._last)}


# ---------------------------------------------------------- replay diff
def event_timeline_diff(recorded: list[dict],
                        replayed: list[dict]) -> dict:
    """Compare two event timelines for ``scripts/replay.py``: which
    kinds appeared/disappeared, whose counts moved, and where the
    kind *order* first diverges. Timestamps are deliberately ignored —
    replay runs at a different wall clock; causality is the contract."""
    rec_counts = Counter(e.get("kind") for e in recorded)
    rep_counts = Counter(e.get("kind") for e in replayed)
    missing = sorted(set(rec_counts) - set(rep_counts))
    extra = sorted(set(rep_counts) - set(rec_counts))
    counts = {kind: {"recorded": rec_counts.get(kind, 0),
                     "replayed": rep_counts.get(kind, 0)}
              for kind in sorted(set(rec_counts) | set(rep_counts))
              if rec_counts.get(kind, 0) != rep_counts.get(kind, 0)}
    rec_kinds = [e.get("kind") for e in recorded]
    rep_kinds = [e.get("kind") for e in replayed]
    first = None
    for i, (a, b) in enumerate(zip(rec_kinds, rep_kinds)):
        if a != b:
            first = {"index": i, "recorded": a, "replayed": b}
            break
    if first is None and len(rec_kinds) != len(rep_kinds):
        i = min(len(rec_kinds), len(rep_kinds))
        first = {"index": i,
                 "recorded": rec_kinds[i] if i < len(rec_kinds) else None,
                 "replayed": rep_kinds[i] if i < len(rep_kinds) else None}
    return {"diverged": bool(missing or extra or counts or first),
            "recorded_events": len(recorded),
            "replayed_events": len(replayed),
            "kinds_missing": missing, "kinds_extra": extra,
            "count_divergence": counts, "order_divergence": first}


__all__ = [
    "EVENTS_FORMAT", "EVENTS_VERSION", "KINDS", "SEVERITIES",
    "EventLedger", "EventLedgerConfig", "FleetEventMerger",
    "IncidentDetector", "NO_EVENTS", "event_timeline_diff",
    "parse_events", "resolve_ledger",
]
