"""OpenAI-compatible serving surface: /v1/chat/completions,
/v1/completions, /v1/models over the continuous-batching engine.

The de-facto standard client protocol: anything that speaks the OpenAI
API (SDKs, proxies, eval harnesses) points at this app unchanged.

    app.post("/v1/chat/completions", oa.chat_completions)
    ... or in one line:
    install_openai_routes(app, engine, tokenizer, model="llama-3.2-1b")

Covered request surface: ``messages``/``prompt``, ``max_tokens`` (and
``max_completion_tokens``), ``temperature``, ``top_p``, ``stream``,
``stop`` (up to 4 stop sequences, enforced host-side with the matched
text trimmed and the engine request cancelled), ``user`` (ignored),
``n`` (only 1 — a 400 otherwise, honestly). Responses carry the
standard envelope: ``chat.completion`` / ``text_completion`` objects,
``chatcmpl-``/``cmpl-`` ids, ``finish_reason`` ("stop" for eos/stop
sequence, "length" for the token budget), and token ``usage``.
Streaming is SSE with ``chat.completion.chunk`` deltas (role chunk
first, content chunks after, terminal chunk with finish_reason, then
``data: [DONE]``); engine failures surface as an ``error`` event, never
a clean-looking truncation.
"""

from __future__ import annotations

import json
import secrets
import time
from typing import Any

from ..http.errors import HTTPError
from ..http.response import Raw, Stream
from .engine import Engine, SamplingParams


class _OpenAIError(HTTPError):
    """Renders through the framework's ``{"error": {...}}`` envelope
    with OpenAI's type/param carried in ``details`` — clients key on
    the status code and ``error.message``, which match exactly."""

    def __init__(self, message: str, *, status: int = 400,
                 err_type: str = "invalid_request_error",
                 param: str | None = None) -> None:
        super().__init__(message, status_code=status,
                         details={"type": err_type, "param": param})


def _content_text(content: Any) -> str:
    """Message content: a string, or the documented content-parts form
    ``[{"type": "text", "text": ...}, ...]`` (text parts concatenated;
    non-text parts rejected — no vision here)."""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        texts = []
        for part in content:
            if not isinstance(part, dict) or part.get("type") != "text" \
                    or not isinstance(part.get("text"), str):
                raise _OpenAIError(
                    "only text content parts are supported",
                    param="messages")
            texts.append(part["text"])
        return "".join(texts)
    raise _OpenAIError("message content must be a string or text parts",
                       param="messages")


def _render_messages(messages: list) -> str:
    """Chat template: the simple role-tagged transcript (model-agnostic
    — random-weight bench models have no canonical template; swap in a
    real template via the ``render`` hook for released checkpoints)."""
    parts = []
    for m in messages:
        if not isinstance(m, dict) or "content" not in m:
            raise _OpenAIError("each message needs role and content",
                               param="messages")
        parts.append(f"{m.get('role', 'user')}: "
                     f"{_content_text(m['content'])}")
    parts.append("assistant:")
    return "\n".join(parts)


def _opt(body: dict, key: str, default):
    """OpenAI treats an explicit JSON null like an absent optional."""
    value = body.get(key, default)
    return default if value is None else value


def _params_from(body: dict) -> SamplingParams:
    max_new = _opt(body, "max_completion_tokens",
                   _opt(body, "max_tokens", 128))
    try:
        params = SamplingParams(
            temperature=float(_opt(body, "temperature", 1.0)),
            top_p=float(_opt(body, "top_p", 1.0)),
            max_new_tokens=int(max_new))
        n = int(_opt(body, "n", 1))
    except (TypeError, ValueError) as exc:
        raise _OpenAIError("temperature/top_p/max_tokens/n must be "
                           "numbers", param="max_tokens") from exc
    if not 1 <= params.max_new_tokens <= 4096:
        raise _OpenAIError("max_tokens out of range [1, 4096]",
                           param="max_tokens")
    if n != 1:
        raise _OpenAIError("only n=1 is supported", param="n")
    return params


def _stops_from(body: dict) -> list[str]:
    stop = body.get("stop")
    if stop is None:
        return []
    if isinstance(stop, str):
        stop = [stop]
    if not isinstance(stop, list) or len(stop) > 4 \
            or not all(isinstance(s, str) and s for s in stop):
        raise _OpenAIError("stop must be a string or up to 4 strings",
                           param="stop")
    return stop


def _cut_at_stop(text: str, stops: list[str]) -> tuple[str, bool]:
    """Trim at the earliest stop-sequence match; True when one hit."""
    cut = -1
    for s in stops:
        i = text.find(s)
        if i >= 0 and (cut < 0 or i < cut):
            cut = i
    return (text[:cut], True) if cut >= 0 else (text, False)


class OpenAIRoutes:
    def __init__(self, engine: Engine, tokenizer: Any, *,
                 model: str = "gofr-tpu", render=None) -> None:
        self.engine = engine
        self.tokenizer = tokenizer
        self.model = model
        self.render = render or _render_messages

    # ------------------------------------------------------------- models
    def models(self, ctx) -> Any:
        return Raw({"object": "list",
                    "data": [{"id": self.model, "object": "model",
                              "owned_by": "gofr-tpu"}]})

    # -------------------------------------------------------------- chat
    @staticmethod
    def _tenant_of(ctx) -> str | None:
        """Auth principal -> accounting label (same resolver as the
        native /chat path); usage metering works for OpenAI clients
        authenticated with API keys / JWTs like any other route."""
        resolver = getattr(ctx.container, "tenant_resolver", None)
        return resolver.resolve(ctx.auth_info) if resolver else None

    async def chat_completions(self, ctx) -> Any:
        body = ctx.bind() or {}
        messages = body.get("messages")
        if not messages or not isinstance(messages, list):
            raise _OpenAIError("messages required", param="messages")
        prompt = self.render(messages)
        return await self._complete(body, prompt, chat=True,
                                    tenant=self._tenant_of(ctx))

    async def completions(self, ctx) -> Any:
        body = ctx.bind() or {}
        prompt = body.get("prompt")
        if isinstance(prompt, list):  # the API allows a list of one
            prompt = prompt[0] if prompt else None
        if not prompt or not isinstance(prompt, str):
            raise _OpenAIError("prompt required", param="prompt")
        return await self._complete(body, prompt, chat=False,
                                    tenant=self._tenant_of(ctx))

    # ------------------------------------------------------------ engine
    async def _complete(self, body: dict, prompt: str, *,
                        chat: bool, tenant: str | None = None) -> Any:
        params = _params_from(body)
        stops = _stops_from(body)
        prompt_tokens = self.tokenizer.encode(prompt)
        req = self.engine.submit(prompt_tokens, params, tenant=tenant)
        if req.error:
            # typed scheduler rejects map to OpenAI's taxonomy: rate
            # limits are 429 rate_limit_error with Retry-After, the
            # rest stay 503 server_error
            rej = getattr(req, "reject", None)
            if rej is not None:
                from .scheduler import retry_after_header
                err = _OpenAIError(
                    req.error,
                    status=429 if rej.code == "rate_limited" else 503,
                    err_type="rate_limit_error"
                    if rej.code == "rate_limited" else "server_error")
                err.headers.update(retry_after_header(rej))
                raise err
            raise _OpenAIError(req.error, status=503,
                               err_type="server_error")
        oid = (("chatcmpl-" if chat else "cmpl-")
               + secrets.token_hex(12))
        created = int(time.time())
        if body.get("stream"):
            return Stream(self._sse(req, oid, created, stops, chat))

        tokens: list[int] = []
        stopped = False
        try:
            while True:
                token = await req.out_queue.get()
                if token is None:
                    break
                tokens.append(token)
                if stops:
                    # enforce stop sequences WHILE draining: no slot
                    # burns out its full token budget past a match
                    _, stopped = _cut_at_stop(
                        self.tokenizer.decode(tokens), stops)
                    if stopped:
                        break
        finally:
            if req.finished_at is None:
                # disconnect mid-drain or stop-sequence hit: free the
                # decode slot (mirrors the streaming path's aclose)
                self.engine.cancel(req)
        if req.error:
            raise _OpenAIError(f"generation failed: {req.error}",
                               status=500, err_type="server_error")
        text = self.tokenizer.decode(tokens)
        text, _hit = _cut_at_stop(text, stops)
        stopped = stopped or _hit
        finish = "stop" if (stopped or len(tokens)
                            < params.max_new_tokens) else "length"
        choice = ({"index": 0, "message": {"role": "assistant",
                                           "content": text},
                   "finish_reason": finish} if chat else
                  {"index": 0, "text": text, "finish_reason": finish})
        return Raw({
            "id": oid,
            "object": "chat.completion" if chat else "text_completion",
            "created": created, "model": self.model,
            "choices": [choice],
            "usage": {"prompt_tokens": len(prompt_tokens),
                      "completion_tokens": len(tokens),
                      "total_tokens": len(prompt_tokens) + len(tokens)},
        })

    async def _sse(self, req, oid: str, created: int, stops: list[str],
                   chat: bool):
        def chunk(delta: dict | None, finish: str | None = None) -> str:
            if chat:
                c = {"index": 0, "delta": delta or {},
                     "finish_reason": finish}
            else:
                c = {"index": 0, "text": (delta or {}).get("content", ""),
                     "finish_reason": finish}
            return "data: " + json.dumps({
                "id": oid,
                "object": ("chat.completion.chunk" if chat
                           else "text_completion"),
                "created": created, "model": self.model,
                "choices": [c]}) + "\n\n"

        gen = self.engine.stream_request(req)
        # deltas come from re-decoding the WHOLE accumulated token list
        # (not per-token decode, which mangles multi-byte characters
        # split across tokens); a tail of hold chars stays back while
        # it could still begin a stop sequence
        tokens_acc: list[int] = []
        sent = 0
        hold = max((len(s) for s in stops), default=1) - 1
        stopped = False
        try:
            if chat:
                yield chunk({"role": "assistant"})
            async for token in gen:
                tokens_acc.append(token)
                text = self.tokenizer.decode(tokens_acc)
                cut, stopped = _cut_at_stop(text, stops)
                if stopped:
                    if cut[sent:]:
                        yield chunk({"content": cut[sent:]})
                    break
                emit_to = len(text) - hold
                # a token boundary can split a multi-byte character:
                # the dangling bytes decode as U+FFFD now but become a
                # real character once the rest arrives — hold trailing
                # replacements back (legit ones flush at finalize)
                while emit_to > sent and text[emit_to - 1] == "�":
                    emit_to -= 1
                if emit_to > sent:
                    yield chunk({"content": text[sent:emit_to]})
                    sent = emit_to
            if req.error:
                yield ("data: " + json.dumps(
                    {"error": {"message": req.error,
                               "type": "server_error"}}) + "\n\n")
                return
            if not stopped:
                text = self.tokenizer.decode(tokens_acc)
                if text[sent:]:
                    yield chunk({"content": text[sent:]})
            finish = "stop" if (stopped or len(tokens_acc)
                                < req.params.max_new_tokens) else "length"
            yield chunk(None, finish)
            yield "data: [DONE]\n\n"
        finally:
            await gen.aclose()   # disconnect/stop-seq cancels the engine


def install_openai_routes(app: Any, engine: Engine, tokenizer: Any, *,
                          model: str = "gofr-tpu", render=None
                          ) -> OpenAIRoutes:
    """Register the three OpenAI-compatible routes on an App."""
    routes = OpenAIRoutes(engine, tokenizer, model=model, render=render)
    app.post("/v1/chat/completions", routes.chat_completions)
    app.post("/v1/completions", routes.completions)
    app.get("/v1/models", routes.models)
    return routes
