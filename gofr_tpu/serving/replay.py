"""Deterministic workload replay — the time machine over the capture
plane.

``WorkloadRecorder`` (serving/observability.py) turns live traffic
into a versioned JSONL workload file; this module drives that file
back through a live :class:`~gofr_tpu.serving.engine.Engine` and
reports what changed:

- **Timing**: requests re-inject with the ORIGINAL inter-arrival
  spacing (scaled by ``speed``), or as a closed loop with a fixed
  number in flight (``closed_loop=N`` — stress mode, timing ignored).
- **Determinism**: greedy requests (temperature 0) replayed through an
  engine built with the same model/config and the captured
  ``engine_seed`` are **bit-identical** to the recorded completions —
  sampling is in-graph argmax and the rng rides as an argument, so
  nothing host-side can perturb the tokens. Stochastic requests
  reproduce the seed but their rng offset depends on global pass
  scheduling, so they may diverge; the divergence report says exactly
  where (first divergent token per request).
- **Reporting**: per-request divergences (plus the
  ``app_replay_divergence`` counter on the engine's metrics manager),
  recorded-vs-replayed latency percentiles, and the engine's SLO
  tracker state after the run.

Redacted captures (``capture_redact=True``) carry hashes instead of
token ids and are refused here — they are for shipping load *shapes*
off-box, not for reproduction.
"""

from __future__ import annotations

import json
import time
from typing import Any

from .engine import SamplingParams
from .observability import WORKLOAD_FORMAT, WORKLOAD_VERSION

#: divergence entries kept verbatim in the report (the counter still
#: counts them all)
MAX_DIVERGENCES_REPORTED = 32


# ------------------------------------------------------------- loading
def parse_workload(text: str) -> dict:
    """JSONL text -> ``{"header": ..., "records": [...]}``; validates
    the format/version contract before anything is replayed."""
    lines = [ln for ln in text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError("empty workload file")
    try:
        header = json.loads(lines[0])
    except ValueError as exc:
        raise ValueError(f"workload header is not JSON: {exc}") from exc
    if not isinstance(header, dict) \
            or header.get("format") != WORKLOAD_FORMAT:
        raise ValueError(
            f"not a {WORKLOAD_FORMAT} file (header: {str(header)[:80]})")
    if header.get("version") != WORKLOAD_VERSION:
        raise ValueError(
            f"unsupported workload version {header.get('version')!r} "
            f"(this build reads version {WORKLOAD_VERSION})")
    records = []
    for i, line in enumerate(lines[1:], start=2):
        try:
            rec = json.loads(line)
        except ValueError as exc:
            raise ValueError(f"workload line {i} is not JSON: "
                             f"{exc}") from exc
        if not isinstance(rec, dict) or "t" not in rec:
            raise ValueError(f"workload line {i} is not a request record")
        records.append(rec)
    return {"header": header, "records": records}


def load_workload(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return parse_workload(f.read())


def _params_from(rec: dict) -> SamplingParams:
    p = rec.get("params") or {}
    return SamplingParams(
        temperature=float(p.get("temperature", 0.0)),
        top_p=float(p.get("top_p", 1.0)),
        top_k=int(p.get("top_k", 0)),
        max_new_tokens=int(p.get("max_new_tokens", 128)))


def _pct(values: list, p: float) -> float | None:
    if not values:
        return None
    values = sorted(values)
    return round(values[min(len(values) - 1, int(p * len(values)))], 3)


def _latency_summary(ttfts: list, tpots: list, e2es: list) -> dict:
    return {"p50_ttft_ms": _pct(ttfts, 0.50),
            "p95_ttft_ms": _pct(ttfts, 0.95),
            "p50_tpot_ms": _pct(tpots, 0.50),
            "p95_tpot_ms": _pct(tpots, 0.95),
            "p50_e2e_ms": _pct(e2es, 0.50),
            "p95_e2e_ms": _pct(e2es, 0.95)}


def _first_divergence(recorded: list, replayed: list) -> int:
    """Index of the first token where the streams differ; when one is
    a strict prefix of the other, the index just past the prefix."""
    for i, (a, b) in enumerate(zip(recorded, replayed)):
        if a != b:
            return i
    return min(len(recorded), len(replayed))


# ------------------------------------------------ efficiency divergence
def _waste_shares(goodput: dict | None) -> dict:
    """Waste per cause as a FRACTION of busy time — the scale-free
    form two runs of different lengths can be compared in."""
    if not isinstance(goodput, dict):
        return {}
    busy = float(goodput.get("busy_s") or 0.0)
    if busy <= 0:
        return {}
    return {cause: float(v or 0.0) / busy
            for cause, v in (goodput.get("waste_s") or {}).items()}


def efficiency_divergence(recorded: dict | None,
                          replayed: dict | None) -> list[dict]:
    """Waste causes whose replayed share of busy device time
    materially exceeds the capture's (more than doubled, past a 2%
    absolute floor). A replay that matches every token but doubles
    ``preempt_recompute`` is a scheduler regression the token diff
    cannot see — this names it."""
    rec, rep = _waste_shares(recorded), _waste_shares(replayed)
    if not rec or not rep:
        return []
    out = []
    for cause in sorted(set(rec) | set(rep)):
        a, b = rec.get(cause, 0.0), rep.get(cause, 0.0)
        if b > 2.0 * a + 0.02:
            out.append({"cause": cause,
                        "recorded_share": round(a, 4),
                        "replayed_share": round(b, 4)})
    return out


def cost_divergence(recorded: dict | None, replayed: dict | None, *,
                    ratio: float = 2.0,
                    floor_s: float = 0.0005) -> list[dict]:
    """Dispatch signatures whose replayed mean pass cost materially
    exceeds the capture's (more than ``ratio`` times, past an absolute
    ``floor_s`` so µs-scale jitter on tiny passes never flags). The
    per-signature twin of :func:`efficiency_divergence`: a replay that
    matches every token but doubles the cost of ``decode/2048`` is a
    kernel regression with a name, not a diffuse slowdown. Advisory
    only — purely report, never a gate."""
    if not isinstance(recorded, dict) or not isinstance(replayed, dict):
        return []
    out = []
    for sig in sorted(set(recorded) & set(replayed)):
        rec, rep = recorded.get(sig), replayed.get(sig)
        if not isinstance(rec, dict) or not isinstance(rep, dict):
            continue
        a = float(rec.get("mean_s") or 0.0)
        b = float(rep.get("mean_s") or 0.0)
        if a > 0 and b > ratio * a + floor_s:
            out.append({"signature": sig,
                        "kind": rep.get("kind") or rec.get("kind"),
                        "recorded_mean_s": round(a, 6),
                        "replayed_mean_s": round(b, 6),
                        "ratio": round(b / a, 3)})
    return out


# -------------------------------------------------------------- replay
def load_events(path: str) -> dict:
    """Load a ``GET /debug/events`` capture (gofr-events JSONL) for
    the replay event-timeline diff: ``{"header", "events"}``."""
    from .events import parse_events
    with open(path) as f:
        header, events = parse_events(f.read())
    return {"header": header, "events": events}


def replay_workload(engine: Any, workload: dict, *, speed: float = 1.0,
                    closed_loop: int = 0,
                    timeout_s: float = 300.0,
                    events: dict | None = None) -> dict:
    """Re-inject a parsed workload through ``engine`` and return the
    divergence + latency report. The engine is started if it is not
    running (and left running — the caller owns its lifecycle).

    ``speed`` scales the recorded inter-arrival gaps (2.0 = twice as
    fast); ``closed_loop=N`` ignores timing entirely and keeps N
    requests in flight — the stress mode for saturation testing.

    ``events`` is an optional :func:`load_events` capture recorded
    alongside the workload (``GET /debug/events``); when given, the
    report gains an ``event_divergence`` block comparing the capture's
    event timeline against the events this replay emitted — a replay
    that matches every token but restarts twice or sheds load is a
    behavioral divergence the token diff cannot see.
    """
    header = workload.get("header") or {}
    records = workload.get("records") or []
    if header.get("redacted"):
        raise ValueError(
            "redacted workload: token ids were captured as salted "
            "hashes, so it cannot be re-injected (capture with "
            "capture_redact=False for replayable workloads)")
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    records = sorted(records, key=lambda r: r.get("t", 0.0))
    playable = [r for r in records if r.get("prompt_tokens")]
    goodput = getattr(engine, "goodput", None)
    if goodput is not None and getattr(goodput, "enabled", False):
        # a clean meter for this replay: the report compares the
        # replay's OWN waste breakdown against the capture's
        goodput.reset()
    costs = getattr(engine, "costs", None)
    if costs is not None and getattr(costs, "enabled", False):
        # same deal for the cost observatory: the per-signature table
        # in the report is this replay's, not the engine's lifetime
        costs.reset()
    # seq watermark: only events emitted DURING this replay count
    # toward the event-timeline diff
    ledger = getattr(engine, "events", None)
    events_seq0 = ledger.state()["seq"] \
        if ledger is not None and getattr(ledger, "enabled", False) else 0
    if not getattr(engine, "_running", False):
        engine.start()

    pairs: list = []
    wall0 = time.perf_counter()
    if closed_loop > 0:
        cap = max(1, int(closed_loop))
        for rec in playable:
            while sum(1 for _, q in pairs
                      if q.finished_at is None and q.error is None) >= cap:
                if time.perf_counter() - wall0 > timeout_s:
                    raise TimeoutError("closed-loop replay stalled")
                time.sleep(0.001)
            pairs.append((rec, engine.submit(
                rec["prompt_tokens"], _params_from(rec),
                tenant=rec.get("tenant"))))
    else:
        base = playable[0]["t"] if playable else 0.0
        for rec in playable:
            target = wall0 + (rec["t"] - base) / speed
            wait = target - time.perf_counter()
            if wait > 0:
                time.sleep(wait)
            pairs.append((rec, engine.submit(
                rec["prompt_tokens"], _params_from(rec),
                tenant=rec.get("tenant"))))

    deadline = time.perf_counter() + timeout_s
    while any(q.finished_at is None and q.error is None
              for _, q in pairs):
        if time.perf_counter() > deadline:
            raise TimeoutError(
                f"replay did not finish within {timeout_s}s")
        time.sleep(0.002)
    wall_s = time.perf_counter() - wall0

    # ------------------------------------------------------ divergence
    divergences: list = []
    digest_divergences: list = []
    compared = replay_errors = 0
    for idx, (rec, req) in enumerate(pairs):
        if rec.get("status") != "ok":
            continue  # the recorded run itself failed/cancelled here
        if req.error is not None:
            replay_errors += 1
            divergences.append({"index": idx, "kind": "replay_error",
                                "error": str(req.error)[:200]})
            continue
        compared += 1
        recorded = rec.get("completion_tokens") or []
        replayed = list(req.generated)
        if recorded != replayed:
            divergences.append({
                "index": idx, "kind": "token",
                "first_divergent_token": _first_divergence(recorded,
                                                           replayed),
                "recorded_len": len(recorded),
                "replayed_len": len(replayed)})
        # fingerprint twin of the token diff: the integrity plane
        # stamps a digest on both the capture and the replayed
        # request (serving/integrity.py). A digest mismatch with
        # MATCHING tokens means the fingerprint inputs drifted
        # (params quantization, digest version) — worth naming, since
        # golden probes sealed from the capture would now misfire.
        # Advisory only, never a gate.
        rec_digest = rec.get("digest")
        rep_digest = getattr(req, "digest", None)
        if rec_digest and rep_digest and rec_digest != rep_digest:
            digest_divergences.append({
                "index": idx, "recorded": rec_digest,
                "replayed": rep_digest,
                "tokens_match": recorded == replayed})
    metrics = getattr(engine, "metrics", None)
    if metrics is not None and divergences:
        if metrics.get("app_replay_divergence") is None:
            metrics.new_counter(
                "app_replay_divergence",
                "replayed requests whose token stream diverged from "
                "the recorded completion")
        metrics.add_counter("app_replay_divergence",
                            float(len(divergences)))

    # --------------------------------------------------------- latency
    rec_lat = _latency_summary(
        [r["ttft_ms"] for r in playable if r.get("ttft_ms") is not None],
        [r["tpot_ms"] for r in playable if r.get("tpot_ms") is not None],
        [r["e2e_ms"] for r in playable if r.get("e2e_ms") is not None])
    ttfts, tpots, e2es = [], [], []
    for _, req in pairs:
        if req.ttft_ms is not None:
            ttfts.append(req.ttft_ms)
        end = req.finished_at
        if end is not None:
            e2es.append((end - req.submitted_at) * 1000.0)
            n = len(req.generated)
            if req.first_token_at is not None and n > 1:
                tpots.append((end - req.first_token_at) * 1000.0
                             / (n - 1))
    slo = getattr(engine, "slo", None)
    recorded_goodput = header.get("goodput")
    replayed_goodput = goodput.summary() if goodput is not None \
        and getattr(goodput, "enabled", False) else None
    recorded_costs = header.get("costs")
    replayed_costs = costs.table() if costs is not None \
        and getattr(costs, "enabled", False) else None
    event_divergence = None
    if events is not None:
        from .events import event_timeline_diff
        replayed_events = [
            e for e in (ledger.snapshot() if ledger is not None
                        and getattr(ledger, "enabled", False) else [])
            if e.get("seq", 0) > events_seq0]
        event_divergence = event_timeline_diff(
            events.get("events") or [], replayed_events)
    return {
        "requests": len(records),
        "submitted": len(pairs),
        "skipped": len(records) - len(playable),
        "compared": compared,
        "divergent": len(divergences),
        "bit_identical": compared > 0 and not divergences,
        "divergences": divergences[:MAX_DIVERGENCES_REPORTED],
        "replay_errors": replay_errors,
        "mode": f"closed-loop-{closed_loop}" if closed_loop > 0
                else f"open-loop-x{speed:g}",
        "wall_s": round(wall_s, 3),
        "recorded_latency": rec_lat,
        "replayed_latency": _latency_summary(ttfts, tpots, e2es),
        # efficiency twin of the token diff: same tokens with a
        # doubled waste share is still a regression, and it has a name
        "recorded_goodput": recorded_goodput,
        "replayed_goodput": replayed_goodput,
        "efficiency_divergence": efficiency_divergence(
            recorded_goodput, replayed_goodput),
        # per-signature twin: same tokens, same waste shares, but one
        # kernel's pass cost doubled — the advisory names the signature
        "recorded_costs": recorded_costs,
        "replayed_costs": replayed_costs,
        "cost_divergence": cost_divergence(recorded_costs,
                                           replayed_costs),
        # fingerprint twin: recorded vs replayed output digests
        # (integrity plane); advisory, bounded like the token diff
        "digest_divergence":
            digest_divergences[:MAX_DIVERGENCES_REPORTED],
        # behavioral twin: the flight recorder's event timeline
        # (restarts, sheds, preemptions) compared kind-for-kind
        "event_divergence": event_divergence,
        "slo": slo.state() if slo is not None else None,
    }


def replay_file(engine: Any, path: str, **kw) -> dict:
    """Convenience: :func:`load_workload` + :func:`replay_workload`."""
    return replay_workload(engine, load_workload(path), **kw)


__all__ = ["parse_workload", "load_workload", "load_events",
           "replay_workload", "replay_file", "efficiency_divergence",
           "cost_divergence", "MAX_DIVERGENCES_REPORTED"]
