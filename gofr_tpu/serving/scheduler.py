"""SLO- and tenant-aware admission: the actuator over the sensor plane.

PRs 5-7 built every signal an overload controller needs — per-tenant
device-time attribution (``UsageLedger``), SLO burn rates
(``SLOTracker``), waste-cause goodput accounting — but the engine still
admitted pure FIFO and shed with a blanket 503. This module closes the
loop with a :class:`Scheduler` that REPLACES the engine's waiting queue
(same ``put``/``pop_batch``/``qsize``/``close`` contract as
``native/batch_queue.py``, so every direct-queue caller keeps working)
and adds four policies, all configured by :class:`SchedulerConfig`:

1. **Weighted fair-share admission** — deficit-round-robin over
   per-tenant sub-queues. Each dequeue picks the tenant with the lowest
   device-time share (the ledger's windowed ``device_s`` plus a local
   in-flight debt estimate, divided by the tenant's weight), so a burst
   tenant queues behind its own backlog instead of everyone's. One
   tenant = one sub-queue = strict FIFO: single-tenant traffic is
   bit-identical to the old queue.
2. **Priority lanes** — two lanes (interactive / background); the
   interactive lane always dequeues first. When it still starves behind
   a full batch, the engine preempts the newest background slot through
   its existing preemption-by-recompute machinery (the
   ``preempt_recompute`` goodput ledger prices that decision) and the
   victim re-enters here at the head of its background sub-queue.
3. **Token-bucket rate limits** keyed by the ``TenantResolver`` label:
   requests/s and prompt-tokens/s buckets, refused with a typed
   ``rate_limited`` rejection (429 + ``Retry-After`` at the HTTP
   surface) before the work ever touches the engine.
4. **Burn-rate-driven shedding** — when the ``SLOTracker`` fast burn
   trips, shed the cheapest traffic first (background lane, then
   over-share tenants) instead of refusing uniformly; re-admit as the
   burn recovers (hysteresis), WARN once per episode.

Every decision happens at admission (``put``, submitter threads) or
retire (``note_retire``, fed from ``_finalize_obs``) boundaries — the
decode hot loop only ever calls ``pop_batch``/``qsize``, which are
plain lock-guarded host bookkeeping. gofrlint's hot-path-purity rule
enforces that contract statically (the entry points that touch retire
paths are ``@hot_path_boundary`` with reasons).
"""

from __future__ import annotations

import math
import queue as queue_mod
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from ..analysis import hot_path_boundary
from .events import NO_EVENTS

INTERACTIVE = "interactive"
BACKGROUND = "background"
LANES = (INTERACTIVE, BACKGROUND)

#: rejection causes (the typed-error ``code`` and the metric label)
QUEUE_FULL = "queue_full"
RATE_LIMITED = "rate_limited"
SHED = "shed"


@dataclass
class RateLimit:
    """Per-tenant token buckets. 0 disables that dimension; burst
    defaults to 2x the sustained rate (min 1 request / 1 token)."""

    #: sustained requests per second (0 = unlimited)
    rps: float = 0.0
    #: request burst capacity; None = max(1, 2 * rps)
    burst: float | None = None
    #: sustained prompt tokens per second (0 = unlimited)
    prompt_tps: float = 0.0
    #: prompt-token burst capacity; None = max(1, 2 * prompt_tps)
    prompt_burst: float | None = None


@dataclass
class SchedulerConfig:
    """Admission/scheduling/shedding policy (docs/configs.md has the
    knob table; docs/operations.md the overload runbook)."""

    #: "fair" = weighted fair-share DRR over tenant sub-queues;
    #: "fifo" = global arrival order (the pre-scheduler behavior, kept
    #: as the replay baseline)
    policy: str = "fair"
    #: per-tenant fair-share weights (share is divided by the weight,
    #: so weight 2.0 = entitled to twice the device time); absent
    #: tenants get ``default_weight``
    weights: dict = field(default_factory=dict)
    default_weight: float = 1.0
    #: ledger window the device-time shares are read over
    share_window_s: float = 300.0
    #: tenants whose traffic lands in the background lane (explicit
    #: ``submit(..., lane=...)`` wins over this mapping)
    background_tenants: tuple = ()
    #: per-tenant rate limits keyed by TenantResolver label; the "*"
    #: key applies to every tenant without an explicit entry
    rate_limits: dict = field(default_factory=dict)
    #: interactive head-of-line wait beyond which the engine may
    #: preempt a background slot (0 disables starvation preemption)
    starvation_s: float = 1.0
    #: floor between scheduler-initiated preemptions — one recompute
    #: at a time, never a thrash storm
    preempt_min_interval_s: float = 0.5
    #: burn-rate-driven shedding master switch (inert without an
    #: attached SLOTracker)
    shed: bool = True
    #: hysteresis: a shed episode ends only once the fast burn falls
    #: to ``threshold * shed_exit_ratio`` — flapping admission around
    #: the trip point would shed and re-admit the same tenant per pass
    shed_exit_ratio: float = 0.5
    #: during an episode, interactive traffic is also shed for tenants
    #: whose windowed device-time share exceeds this multiple of the
    #: equal share (background traffic always sheds first)
    shed_overshare: float = 2.0
    #: Retry-After hint (seconds) for queue_full / shed rejections
    retry_after_s: float = 1.0
    #: per-tenant fast-burn window for the ``state()`` burn column and
    #: the contention smoke's victim assertion
    burn_window_s: float = 300.0
    #: per-tenant retire events retained for the burn column
    burn_events: int = 2048


@dataclass
class SchedReject:
    """Typed admission rejection, stamped on the request before
    ``put`` returns False — handlers turn it into 429/503 with a
    ``Retry-After`` header instead of an undifferentiated 503."""

    code: str                 # queue_full | rate_limited | shed
    tenant: str
    retry_after_s: float
    detail: str = ""

    @property
    def message(self) -> str:
        return self.detail or f"admission refused: {self.code}"


class _TokenBucket:
    """Classic token bucket; times come from the caller so the clock
    is mockable and shared across buckets."""

    def __init__(self, rate: float, burst: float) -> None:
        self.rate = max(0.0, float(rate))
        self.burst = max(1.0, float(burst))
        self.level = self.burst
        self._last = None  # type: float | None

    def try_take(self, n: float, now: float) -> float:
        """0.0 on success; else seconds until ``n`` tokens exist (the
        Retry-After hint). Disabled buckets (rate 0) always admit."""
        if self.rate <= 0:
            return 0.0
        if self._last is None:
            self._last = now
        self.level = min(self.burst,
                         self.level + (now - self._last) * self.rate)
        self._last = now
        if self.level >= n:
            self.level -= n
            return 0.0
        return (n - self.level) / self.rate


class _TenantState:
    """Per-tenant scheduler bookkeeping (guarded by the Scheduler
    lock): sub-queues per lane, fair-share debt, rate buckets, and the
    retire-outcome ring behind the per-tenant burn column."""

    def __init__(self, limit: RateLimit | None,
                 burn_events: int) -> None:
        self.queues: dict[str, deque] = {lane: deque() for lane in LANES}
        #: in-flight device-time debt (seconds-equivalent) accumulated
        #: per dequeue and cleared at every ledger refresh — without
        #: it, a burst tenant would win every pick between refreshes
        self.debt = 0.0
        #: ledger-fed windowed device seconds at the last refresh
        self.share_s = 0.0
        self.req_bucket: _TokenBucket | None = None
        self.tok_bucket: _TokenBucket | None = None
        if limit is not None:
            if limit.rps > 0:
                self.req_bucket = _TokenBucket(
                    limit.rps,
                    limit.burst if limit.burst is not None
                    else max(1.0, 2.0 * limit.rps))
            if limit.prompt_tps > 0:
                self.tok_bucket = _TokenBucket(
                    limit.prompt_tps,
                    limit.prompt_burst if limit.prompt_burst is not None
                    else max(1.0, 2.0 * limit.prompt_tps))
        #: (t, bad) retire outcomes over the burn window
        self.outcomes: deque = deque(maxlen=max(16, int(burn_events)))
        self.outcomes_bad = 0

    def depth(self) -> int:
        return sum(len(q) for q in self.queues.values())


class Scheduler:
    """Drop-in replacement for the engine's waiting queue with
    tenant/lane/SLO-aware admission. Thread-safe: ``put`` runs on
    submitter (HTTP handler) threads, ``pop_batch``/``qsize`` on the
    engine thread, ``state()`` on debug-route threads."""

    def __init__(self, config: SchedulerConfig | None = None,
                 capacity: int = 0, *, ledger: Any = None,
                 slo_source: Any = None, metrics: Any = None,
                 logger: Any = None) -> None:
        self.config = config if config is not None else SchedulerConfig()
        if self.config.policy not in ("fair", "fifo"):
            raise ValueError(f"scheduler policy must be 'fair' or "
                             f"'fifo', got {self.config.policy!r}")
        self.capacity = max(0, int(capacity))
        #: UsageLedger the fair-share device-time shares are read from
        self.ledger = ledger
        #: zero-arg callable returning the engine's SLOTracker (or
        #: None) — resolved per check because ``app.serve_model``
        #: attaches the tracker after the engine (and this queue) exist
        self.slo_source = slo_source
        self.metrics = metrics
        self.logger = logger
        #: EventLedger admission decisions are recorded on; replaced by
        #: ``app.serve_model`` with the engine's ledger (NO_EVENTS is a
        #: no-op sink, so standalone schedulers stay silent, not broken)
        self.events = NO_EVENTS
        self._lock = threading.Condition()
        self._tenants: dict[str, _TenantState] = {}
        self._size = 0
        self._closed = False
        self._seq = 0                 # global arrival order (fifo mode
        #                               and FIFO-within-sub-queue ties)
        self._share_refreshed = 0.0   # ledger-share cache timestamp
        self._spt = 1e-4              # est. device seconds per token,
        #                               re-fit from the ledger rollup
        self._shed_active = False
        self._shed_since: float | None = None
        self._last_preempt = 0.0
        self._slo_checked = 0.0
        self._slo_tripped = False     # cached fast-burn trip state
        self._slo_burn = 0.0
        self.counters = {"admitted": 0, "dequeued": 0, "readmitted": 0,
                         "preemptions": 0, "shed_episodes": 0,
                         "rejected": {QUEUE_FULL: 0, RATE_LIMITED: 0,
                                      SHED: 0}}

    # ------------------------------------------------------------ config
    def reconfigure(self, config: SchedulerConfig) -> None:
        """Swap the policy in place (``app.serve_model(scheduler=...)``
        runs after the engine — and this queue — were constructed).
        Queued requests are re-bucketed under the new config in global
        arrival order; counters and burn history survive."""
        if config.policy not in ("fair", "fifo"):
            raise ValueError(f"scheduler policy must be 'fair' or "
                             f"'fifo', got {config.policy!r}")
        with self._lock:
            queued: list = []
            for ts in self._tenants.values():
                for lane in LANES:
                    queued.extend(ts.queues[lane])
                    ts.queues[lane].clear()
            queued.sort(key=lambda pair: pair[0])
            old = self._tenants
            self.config = config
            self._tenants = {}
            for name, ts in old.items():
                fresh = self._tenant_locked(name)
                fresh.outcomes = ts.outcomes
                fresh.outcomes_bad = ts.outcomes_bad
            for seq, req in queued:
                lane = self._lane_for(req)
                req.lane = lane
                self._tenant_locked(self._label(req)).queues[lane] \
                    .append((seq, req))
            self._share_refreshed = 0.0  # force a share re-read
            self._lock.notify_all()

    # ----------------------------------------------------------- helpers
    @staticmethod
    def _label(req: Any) -> str:
        return getattr(req, "tenant", None) or "anonymous"

    def _lane_for(self, req: Any) -> str:
        lane = getattr(req, "lane", None)
        if lane in LANES and lane != INTERACTIVE:
            return lane  # explicit background assignment wins
        if self._label(req) in self.config.background_tenants:
            return BACKGROUND
        return lane if lane in LANES else INTERACTIVE

    def _tenant_locked(self, name: str) -> _TenantState:
        ts = self._tenants.get(name)
        if ts is None:
            limits = self.config.rate_limits
            limit = limits.get(name, limits.get("*"))
            ts = _TenantState(limit, self.config.burn_events)
            self._tenants[name] = ts
        return ts

    def _weight(self, name: str) -> float:
        return max(1e-6, float(self.config.weights.get(
            name, self.config.default_weight)))

    def _refresh_shares_locked(self, now: float) -> None:
        """Pull windowed per-tenant device seconds from the usage
        ledger (throttled — rollup takes the ledger lock) and re-fit
        the seconds-per-token estimate the in-flight debt uses."""
        if now - self._share_refreshed < 0.5:
            return
        self._share_refreshed = now
        for ts in self._tenants.values():
            ts.share_s = 0.0
            ts.debt = 0.0
        if self.ledger is None:
            return
        try:
            rollup = self.ledger.rollup(
                window_s=self.config.share_window_s)
        except Exception:
            return  # accounting must never block admission
        device_total = tokens_total = 0.0
        for name, tot in (rollup.get("tenants") or {}).items():
            device_s = float(tot.get("device_s", 0.0))
            self._tenant_locked(name).share_s = device_s
            device_total += device_s
            tokens_total += (tot.get("prompt_tokens", 0)
                             + tot.get("completion_tokens", 0))
        if device_total > 0 and tokens_total > 0:
            self._spt = device_total / tokens_total

    def _est_cost_s(self, req: Any) -> float:
        """In-flight device-time debt for one dequeue: prompt plus the
        full generation budget, priced at the fitted sec/token."""
        tokens = len(getattr(req, "prompt_tokens", ()) or ())
        params = getattr(req, "params", None)
        tokens += int(getattr(params, "max_new_tokens", 0) or 0)
        return max(1, tokens) * self._spt

    def _pick_locked(self, now: float) -> Any | None:
        """Dequeue one request: interactive lane strictly first; within
        a lane, the tenant with the lowest weighted device-time share
        (ledger share + in-flight debt, over the weight) — the DRR
        deficit, fed by real accounting instead of a fixed quantum.
        FIFO policy ignores all of it and takes global arrival order."""
        if self.config.policy == "fifo":
            best = None
            for ts in self._tenants.values():
                for lane in LANES:
                    q = ts.queues[lane]
                    if q and (best is None or q[0][0] < best[0][0]):
                        best = (q[0], q)
            if best is None:
                return None
            (seq, req), q = best
            q.popleft()
            return req
        self._refresh_shares_locked(now)
        for lane in LANES:
            best_name = None
            best_score = (0.0, 0)
            for name, ts in self._tenants.items():
                q = ts.queues[lane]
                if not q:
                    continue
                score = ((ts.share_s + ts.debt) / self._weight(name),
                         q[0][0])  # arrival order breaks share ties
                if best_name is None or score < best_score:
                    best_name, best_score = name, score
            if best_name is not None:
                ts = self._tenants[best_name]
                _, req = ts.queues[lane].popleft()
                ts.debt += self._est_cost_s(req)
                return req
        return None

    # ------------------------------------------------------------- admit
    def _check_shed_locked(self, now: float) -> None:
        """Refresh the cached fast-burn state (throttled — state()
        takes the tracker lock) and run the episode hysteresis: enter
        at the trip threshold, exit at threshold * shed_exit_ratio."""
        if not self.config.shed:
            self._shed_active = False
            return
        if now - self._slo_checked < 0.25:
            pass
        else:
            self._slo_checked = now
            slo = self.slo_source() if callable(self.slo_source) else None
            if slo is None:
                self._slo_tripped = False
                self._slo_burn = 0.0
            else:
                try:
                    fast = slo.state().get("fast_burn") or {}
                except Exception:
                    fast = {}
                self._slo_burn = float(fast.get("burn_rate") or 0.0)
                threshold = float(fast.get("threshold") or 0.0)
                if not self._shed_active:
                    self._slo_tripped = bool(fast.get("tripped"))
                else:  # hysteresis: stay shedding until well below
                    exit_at = threshold * self.config.shed_exit_ratio
                    self._slo_tripped = (threshold > 0
                                         and self._slo_burn > exit_at)
        if self._slo_tripped and not self._shed_active:
            self._shed_active = True
            self._shed_since = now
            self.counters["shed_episodes"] += 1
            if self.logger is not None:
                self.logger.warn(
                    "overload shed episode: SLO fast burn tripped — "
                    "shedding background and over-share traffic until "
                    "the burn recovers",
                    burn_rate=round(self._slo_burn, 2))
            self.events.emit("sched.shed_open", severity="warn",
                             cause="fast_burn",
                             burn_rate=round(self._slo_burn, 2))
        elif not self._slo_tripped and self._shed_active:
            self._shed_active = False
            self._shed_since = None
            self.events.emit("sched.shed_close",
                             burn_rate=round(self._slo_burn, 2))

    def _shed_verdict_locked(self, req: Any, lane: str,
                             now: float) -> bool:
        """True = refuse this request under the active shed episode.
        Cheapest traffic first: all background, then interactive from
        tenants holding more than ``shed_overshare`` x the equal
        share of the windowed device time."""
        if not self._shed_active:
            return False
        if lane == BACKGROUND:
            return True
        self._refresh_shares_locked(now)
        active = [ts.share_s for ts in self._tenants.values()
                  if ts.share_s > 0]
        total = sum(active)
        if total <= 0 or len(active) < 2:
            return False  # nobody is measurably over-share yet
        fair = total / len(active)
        mine = self._tenant_locked(self._label(req)).share_s
        return mine > self.config.shed_overshare * fair

    def _reject_locked(self, req: Any, code: str, tenant: str,
                       retry_after_s: float, detail: str) -> bool:
        req.reject = SchedReject(code=code, tenant=tenant,
                                 retry_after_s=retry_after_s,
                                 detail=detail)
        self.counters["rejected"][code] += 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_sched_rejections",
                                           cause=code, tenant=tenant)
        self.events.emit("sched.reject", severity="warn",
                         request_id=getattr(req, "request_id", None),
                         tenant=tenant, cause=code,
                         retry_after_s=round(retry_after_s, 3))
        return False

    @hot_path_boundary(
        "admission boundary: runs on submitter threads before any work reaches the engine loop")
    def put(self, item: Any) -> bool:
        """Admit or refuse one request. False = refused; a typed
        :class:`SchedReject` is stamped on the request for every
        policy refusal (closed queues stamp nothing — the engine's
        'not accepting requests' failure stands)."""
        now = time.monotonic()
        with self._lock:
            if self._closed:
                return False
            tenant = self._label(item)
            lane = self._lane_for(item)
            item.lane = lane
            ts = self._tenant_locked(tenant)
            readmit = bool(getattr(item, "_sched_readmit", False))
            if not readmit:
                # 1) per-tenant rate limits: refused before the work
                #    touches anything (the 429 + Retry-After surface)
                wait = 0.0
                if ts.req_bucket is not None:
                    wait = ts.req_bucket.try_take(1.0, now)
                if wait <= 0 and ts.tok_bucket is not None:
                    n = float(len(getattr(item, "prompt_tokens", ())
                                  or ()) or 1)
                    wait = ts.tok_bucket.try_take(n, now)
                if wait > 0:
                    return self._reject_locked(
                        item, RATE_LIMITED, tenant, wait,
                        f"rate limit exceeded for tenant {tenant!r}")
                # 2) burn-rate shedding: cheapest traffic first
                self._check_shed_locked(now)
                if self._shed_verdict_locked(item, lane, now):
                    return self._reject_locked(
                        item, SHED, tenant, self.config.retry_after_s,
                        "shedding load: SLO error budget burning too "
                        "fast (fast-burn episode active)")
                # 3) admission bound (already-admitted work re-entering
                #    through readmit() is exempt, like the old
                #    _requeued list was)
                if self.capacity and self._size >= self.capacity:
                    return self._reject_locked(
                        item, QUEUE_FULL, tenant,
                        self.config.retry_after_s,
                        "engine overloaded: waiting queue full")
            self._seq += 1
            entry = (self._seq, item)
            if readmit:
                item._sched_readmit = False
                ts.queues[lane].appendleft((-self._seq, item))
                self.counters["readmitted"] += 1
            else:
                ts.queues[lane].append(entry)
                self.counters["admitted"] += 1
            self._size += 1
            self._lock.notify()
            return True

    def readmit(self, req: Any) -> None:
        """Re-enter already-admitted work (a scheduler-initiated
        preemption victim) at the HEAD of its lane sub-queue, exempt
        from the bound, buckets and shedding — its admission was
        already paid. The engine calls this after pulling the victim
        back out of its ``_requeued`` fast lane, which would otherwise
        hand the freed slot straight back."""
        req._sched_readmit = True
        self.put(req)

    # ----------------------------------------------------------- dequeue
    def pop_batch(self, max_n: int, first_wait_s: float = 0.1,
                  drain_wait_s: float = 0.0) -> list | None:
        """Same contract as ``native/batch_queue.py``: block up to
        ``first_wait_s`` for one item, drain up to ``max_n`` (waiting
        ``drain_wait_s`` for stragglers). ``None`` = closed and
        drained; ``[]`` = timed out."""
        max_n = max(0, int(max_n))
        out: list = []
        with self._lock:
            deadline = time.monotonic() + max(0.0, first_wait_s)
            while self._size == 0:
                if self._closed:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return out
                self._lock.wait(timeout=min(remaining, 0.05))
            now = time.monotonic()
            while len(out) < max_n and self._size > 0:
                req = self._pick_locked(now)
                if req is None:  # size drifted (defensive)
                    break
                self._size -= 1
                self.counters["dequeued"] += 1
                out.append(req)
            if out and len(out) < max_n and drain_wait_s > 0:
                straggler_deadline = time.monotonic() + drain_wait_s
                while len(out) < max_n:
                    if self._size == 0:
                        remaining = (straggler_deadline
                                     - time.monotonic())
                        if remaining <= 0 or self._closed:
                            break
                        self._lock.wait(timeout=min(remaining, 0.05))
                        continue
                    req = self._pick_locked(time.monotonic())
                    if req is None:
                        break
                    self._size -= 1
                    self.counters["dequeued"] += 1
                    out.append(req)
        return out

    def get_nowait(self) -> Any:
        """queue.Queue-compatible accessor (raises queue.Empty)."""
        batch = self.pop_batch(1, first_wait_s=0.0)
        if not batch:
            raise queue_mod.Empty
        return batch[0]

    def qsize(self) -> int:
        with self._lock:
            return self._size

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    def reopen(self) -> None:
        """Reverse :meth:`close` — the restartable-engine lifecycle:
        ``Engine.start()`` after ``stop()`` reopens admission on the
        same scheduler, keeping tenant state, counters and rate-bucket
        levels (a restart is not an amnesty)."""
        with self._lock:
            self._closed = False

    # ------------------------------------------------------- starvation
    def starving_interactive(self) -> bool:
        """True when the engine should preempt a background slot: the
        interactive head-of-line request has waited past
        ``starvation_s`` with the batch full, and the preemption rate
        cap allows another recompute. Called once per engine pass with
        zero free slots — cheap lock-guarded reads."""
        cfg = self.config
        if cfg.policy != "fair" or cfg.starvation_s <= 0:
            return False
        now = time.monotonic()
        wall = time.time()
        with self._lock:
            if now - self._last_preempt < cfg.preempt_min_interval_s:
                return False
            for ts in self._tenants.values():
                q = ts.queues[INTERACTIVE]
                if not q:
                    continue
                head = q[0][1]
                age = wall - getattr(head, "submitted_at", wall)
                if age > cfg.starvation_s:
                    # arm the rate cap on the DECISION (victimless
                    # attempts must not re-fire every pass); the
                    # engine reports the actual preemption via
                    # note_preempted()
                    self._last_preempt = now
                    return True
        return False

    def note_preempted(self) -> None:
        """The engine actually preempted a background slot for the
        starving interactive lane — count it."""
        with self._lock:
            self.counters["preemptions"] += 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_sched_preemptions")
        self.events.emit("sched.preempt", severity="warn",
                         cause="starvation")

    # ----------------------------------------------------------- retire
    @hot_path_boundary(
        "retire boundary: per-tenant burn bookkeeping fed from _finalize_obs, off the decode loop")
    def note_retire(self, tenant: str | None, good: bool,
                    t: float | None = None) -> None:
        """Record one retired request's SLO verdict against its
        tenant — the per-tenant fast-burn column ``state()`` and the
        contention smoke read. The verdict is the same ``judge()``
        result the global tracker gets; this just keys it by tenant."""
        t = time.time() if t is None else t
        with self._lock:
            ts = self._tenant_locked(tenant or "anonymous")
            if len(ts.outcomes) == ts.outcomes.maxlen:
                ts.outcomes_bad -= ts.outcomes[0][1]
            bad = 0 if good else 1
            ts.outcomes.append((t, bad))
            ts.outcomes_bad += bad

    def _tenant_burn_locked(self, ts: _TenantState, now: float,
                            availability: float) -> dict:
        window = self.config.burn_window_s
        cutoff = now - window
        while ts.outcomes and ts.outcomes[0][0] < cutoff:
            _, bad = ts.outcomes.popleft()
            ts.outcomes_bad -= bad
        total = len(ts.outcomes)
        err = (ts.outcomes_bad / total) if total else 0.0
        budget = max(1e-9, 1.0 - availability)
        return {"total": total, "bad": ts.outcomes_bad,
                "burn_rate": round(err / budget, 4)}

    # ------------------------------------------------------------- state
    def state(self, fresh: bool = False) -> dict:
        """The ``GET /debug/scheduler`` payload: policy, lane depths,
        per-tenant shares/weights/queues/burn, rate-limit levels, shed
        episode state and the admission counters. ``fresh=True``
        bypasses the 0.5s share-cache throttle so the view reflects
        every retire that already landed — the ``?fresh=1`` debug
        query the smokes use instead of sleeping out the window."""
        now_m = time.monotonic()
        wall = time.time()
        slo = self.slo_source() if callable(self.slo_source) else None
        availability = getattr(getattr(slo, "config", None),
                               "availability", 0.999)
        with self._lock:
            if fresh:
                self._share_refreshed = 0.0
            self._refresh_shares_locked(now_m)
            lanes = {lane: sum(len(ts.queues[lane])
                               for ts in self._tenants.values())
                     for lane in LANES}
            total_share = sum(ts.share_s
                              for ts in self._tenants.values())
            tenants = {}
            for name, ts in sorted(self._tenants.items()):
                info = {
                    "queued": {lane: len(ts.queues[lane])
                               for lane in LANES},
                    "weight": self._weight(name),
                    "device_share_s": round(ts.share_s, 6),
                    "device_share": round(
                        ts.share_s / total_share, 4)
                    if total_share > 0 else 0.0,
                    "burn": self._tenant_burn_locked(ts, wall,
                                                     availability),
                }
                if ts.req_bucket is not None:
                    info["rps_bucket_level"] = round(
                        ts.req_bucket.level, 3)
                if ts.tok_bucket is not None:
                    info["tps_bucket_level"] = round(
                        ts.tok_bucket.level, 3)
                tenants[name] = info
            counters = {**self.counters,
                        "rejected": dict(self.counters["rejected"])}
            return {
                "policy": self.config.policy,
                "capacity": self.capacity,
                "depth": self._size,
                "lanes": lanes,
                "tenants": tenants,
                "share_window_s": self.config.share_window_s,
                "burn_window_s": self.config.burn_window_s,
                "shedding": {
                    "enabled": self.config.shed,
                    "active": self._shed_active,
                    "for_s": round(now_m - self._shed_since, 3)
                    if self._shed_since is not None else None,
                    "fast_burn_rate": round(self._slo_burn, 4),
                    "exit_ratio": self.config.shed_exit_ratio,
                },
                "counters": counters,
            }

    def publish_gauges(self, metrics: Any) -> None:
        """Throttled gauge pass, called from the engine's
        ``_update_gauges``: lane depths, per-tenant windowed share and
        the shed flag. Counters (rejections, preemptions) are written
        at the events themselves."""
        with self._lock:
            self._refresh_shares_locked(time.monotonic())
            lanes = {lane: float(sum(len(ts.queues[lane])
                                     for ts in self._tenants.values()))
                     for lane in LANES}
            total = sum(ts.share_s for ts in self._tenants.values())
            shares = {name: (ts.share_s / total if total > 0 else 0.0)
                      for name, ts in self._tenants.items()}
            shed = self._shed_active
        for lane, depth in lanes.items():
            metrics.set_gauge("app_sched_lane_depth", depth, lane=lane)
        for name, share in shares.items():
            metrics.set_gauge("app_sched_tenant_share",
                              round(share, 4), tenant=name)
        metrics.set_gauge("app_sched_shed_active", 1.0 if shed else 0.0)


def retry_after_header(reject: SchedReject) -> dict:
    """``Retry-After`` header for a typed rejection (whole seconds,
    rounded up, floor 1 — RFC 7231 wants an integer)."""
    return {"Retry-After": str(max(1, math.ceil(reject.retry_after_s)))}


__all__ = ["Scheduler", "SchedulerConfig", "SchedReject", "RateLimit",
           "retry_after_header", "INTERACTIVE", "BACKGROUND",
           "QUEUE_FULL", "RATE_LIMITED", "SHED"]
