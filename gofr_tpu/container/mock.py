"""Mock container — the centerpiece of the test strategy.

Mirrors reference ``NewMockContainer`` (container/mock_container.go:93-160):
a full container whose every capability is an in-memory fake with call
recording, so handler tests run hermetically. SQL is backed by
in-memory sqlite, KV by a dict, pub/sub by an in-process broker, and
the TPU slot by a CPU-backed fake runtime — the "miniredis for the
device layer" SURVEY §4 calls for.
"""

from __future__ import annotations

from typing import Any

from ..config.env import DictConfig
from ..logging.logger import DEBUG, MockLogger
from ..tracing.tracer import InMemoryExporter, Tracer
from .container import Container


class CallRecorder:
    """Records method calls; configurable canned results/raises."""

    def __init__(self, name: str = "mock") -> None:
        self._name = name
        self.calls: list[tuple[str, tuple, dict]] = []
        # mocked capabilities report healthy unless a test says otherwise,
        # so health assertions stay hermetic
        self._results: dict[str, Any] = {"health_check": {"status": "UP"}}
        self._raises: dict[str, BaseException] = {}

    def expect(self, method: str, result: Any = None,
               raises: BaseException | None = None) -> None:
        if raises is not None:
            self._raises[method] = raises
        else:
            self._results[method] = result

    def calls_to(self, method: str) -> list[tuple[tuple, dict]]:
        return [(a, k) for m, a, k in self.calls if m == method]

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        def call(*args: Any, **kwargs: Any) -> Any:
            self.calls.append((method, args, kwargs))
            if method in self._raises:
                raise self._raises[method]
            return self._results.get(method)
        return call


class MockContainer(Container):
    def __init__(self, config: DictConfig | None = None) -> None:
        super().__init__(config=config or DictConfig(),
                         logger=MockLogger(level=DEBUG))
        self.register_framework_metrics()
        self.trace_exporter = InMemoryExporter()
        self.tracer = Tracer(service_name="mock-app", exporter=self.trace_exporter)
        self.mocks: dict[str, CallRecorder] = {}
        # real in-memory backends by default (sqlite SQL, dict KV,
        # in-process redis) so handler tests exercise actual query paths;
        # mock(slot) swaps any of them for a CallRecorder
        from ..datasource.kv import InMemoryKV
        from ..datasource.redis import Redis
        from ..datasource.sql import SQL
        self.add_sql(SQL(database=":memory:"))
        self.add_redis(Redis())
        self.add_kv_store(InMemoryKV())

    def mock(self, slot: str) -> CallRecorder:
        """Install a CallRecorder at a container slot and return it."""
        recorder = self.mocks.get(slot)
        if recorder is None:
            recorder = CallRecorder(slot)
            self.mocks[slot] = recorder
            setattr(self, slot, recorder)
        return recorder

    def mock_service(self, name: str) -> CallRecorder:
        recorder = CallRecorder(f"service:{name}")
        self.services[name] = recorder
        self.mocks[f"service:{name}"] = recorder
        return recorder

    @property
    def log_lines(self) -> list[dict]:
        return self.logger.lines  # type: ignore[attr-defined]


def new_mock_container() -> MockContainer:
    return MockContainer()
