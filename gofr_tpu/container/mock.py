"""Mock container — the centerpiece of the test strategy.

Mirrors reference ``NewMockContainer`` (container/mock_container.go:93-160):
a full container whose every capability is an in-memory fake with call
recording, so handler tests run hermetically. SQL is backed by
in-memory sqlite, KV by a dict, pub/sub by an in-process broker, and
the TPU slot by a CPU-backed fake runtime — the "miniredis for the
device layer" SURVEY §4 calls for.
"""

from __future__ import annotations

from typing import Any

from ..config.env import DictConfig
from ..logging.logger import DEBUG, MockLogger
from ..tracing.tracer import InMemoryExporter, Tracer
from .container import Container


class ExpectationError(AssertionError):
    """An expectation was violated: unexpected call, argument
    mismatch, or unmet count at verify() (the analog of a gomock
    controller failing the test, reference
    container/mock_container.go:93)."""


_ANY = object()


class Expectation:
    """One expected interaction, gomock-style: chain ``with_args``,
    ``returns``/``raises``, and ``times`` (exact count; default "at
    least once")."""

    def __init__(self, method: str) -> None:
        self.method = method
        self.args: Any = _ANY
        self.kwargs: Any = _ANY
        self.result: Any = None
        self.exc: BaseException | None = None
        self.expected_times: int | None = None
        self.actual = 0

    def with_args(self, *args: Any, **kwargs: Any) -> "Expectation":
        self.args = args
        self.kwargs = kwargs
        return self

    def returns(self, result: Any) -> "Expectation":
        self.result = result
        return self

    def raises(self, exc: BaseException) -> "Expectation":
        self.exc = exc
        return self

    def times(self, n: int) -> "Expectation":
        self.expected_times = n
        return self

    # -- matching
    def matches(self, args: tuple, kwargs: dict) -> bool:
        if self.args is not _ANY and tuple(self.args) != tuple(args):
            return False
        if self.kwargs is not _ANY and self.kwargs != kwargs:
            return False
        return True

    def saturated(self) -> bool:
        return self.expected_times is not None \
            and self.actual >= self.expected_times

    def describe(self) -> str:
        want = "any args" if self.args is _ANY else \
            f"args={self.args!r} kwargs={self.kwargs!r}"
        count = "at least once" if self.expected_times is None \
            else f"exactly {self.expected_times}x"
        return f"{self.method}({want}) {count}, called {self.actual}x"

    def unmet(self) -> bool:
        if self.expected_times is None:
            return self.actual == 0
        return self.actual != self.expected_times


class CallRecorder:
    """Records method calls; configurable canned results/raises.

    Two modes compose:

      * loose (default): any method call succeeds and returns the
        canned result set via :meth:`expect` — handler tests that only
        care about one interaction stay one-liners;
      * strict expectations via :meth:`expect_call`: declared
        interactions are matched (by method, then args) in declaration
        order per method; ``verify()`` fails on unmet counts, and once
        a method has ANY declared expectation, a call that matches
        none of them fails immediately.
    """

    def __init__(self, name: str = "mock") -> None:
        self._name = name
        self.calls: list[tuple[str, tuple, dict]] = []
        # mocked capabilities report healthy unless a test says otherwise,
        # so health assertions stay hermetic
        self._results: dict[str, Any] = {"health_check": {"status": "UP"}}
        self._raises: dict[str, BaseException] = {}
        self._expectations: list[Expectation] = []

    def expect(self, method: str, result: Any = None,
               raises: BaseException | None = None) -> None:
        if raises is not None:
            self._raises[method] = raises
        else:
            self._results[method] = result

    def expect_call(self, method: str) -> Expectation:
        exp = Expectation(method)
        self._expectations.append(exp)
        return exp

    def verify(self) -> None:
        unmet = [e.describe() for e in self._expectations if e.unmet()]
        if unmet:
            raise ExpectationError(
                f"{self._name}: unmet expectations:\n  " +
                "\n  ".join(unmet))

    def calls_to(self, method: str) -> list[tuple[tuple, dict]]:
        return [(a, k) for m, a, k in self.calls if m == method]

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        def call(*args: Any, **kwargs: Any) -> Any:
            self.calls.append((method, args, kwargs))
            declared = [e for e in self._expectations
                        if e.method == method]
            if declared:
                for exp in declared:
                    if not exp.saturated() and exp.matches(args, kwargs):
                        exp.actual += 1
                        if exp.exc is not None:
                            raise exp.exc
                        return exp.result
                raise ExpectationError(
                    f"{self._name}.{method} called with args={args!r} "
                    f"kwargs={kwargs!r}, matching no open expectation "
                    f"(declared: "
                    f"{[e.describe() for e in declared]})")
            if method in self._raises:
                raise self._raises[method]
            return self._results.get(method)
        return call


class _SQLExpectation:
    """One expected statement: regex-matched SQL, optional exact args,
    canned rows / rowcount / error."""

    def __init__(self, kind: str, pattern: str) -> None:
        import re
        self.kind = kind  # "query" | "exec"
        self.pattern = re.compile(pattern, re.IGNORECASE | re.DOTALL)
        self.args: Any = _ANY
        self.rows: list[dict] = []
        self.rowcount = 0
        self.exc: BaseException | None = None
        self.consumed = False

    def with_args(self, *args: Any) -> "_SQLExpectation":
        self.args = args
        return self

    def returns(self, rows: list[dict]) -> "_SQLExpectation":
        self.rows = rows
        return self

    # (affects() feeds _ExecResult.rowcount — crud's not-found checks
    # read it exactly as they read a real cursor's)

    def affects(self, rowcount: int) -> "_SQLExpectation":
        self.rowcount = rowcount
        return self

    def raises(self, exc: BaseException) -> "_SQLExpectation":
        self.exc = exc
        return self

    def describe(self) -> str:
        want = "" if self.args is _ANY else f" args={self.args!r}"
        return f"{self.kind} /{self.pattern.pattern}/{want}"


class _ExecResult:
    """What SQLMock.exec returns: the cursor attributes statement-
    issuing code actually reads."""

    def __init__(self, rowcount: int) -> None:
        self.rowcount = rowcount
        self.lastrowid = 0


class SQLMock:
    """sqlmock-style SQL double (reference container/sql_mock.go:12):
    every statement the code under test issues must match the next
    declared expectation of its kind in order; rows/rowcounts are
    canned; ``verify()`` fails the test on statements never issued.

    Presents the same surface as ``datasource.sql.SQL`` (query /
    query_row / exec / select / begin / ph), so it drops into
    ``container.sql``. ``begin()`` yields the mock itself — declared
    expectations span transactions, exactly like sqlmock."""

    dialect = "sqlite"

    def __init__(self, *, ordered: bool = True) -> None:
        self.ordered = ordered
        self._expectations: list[_SQLExpectation] = []
        self.statements: list[tuple[str, str, tuple]] = []

    # ---- declaration
    def expect_query(self, pattern: str) -> _SQLExpectation:
        exp = _SQLExpectation("query", pattern)
        self._expectations.append(exp)
        return exp

    def expect_exec(self, pattern: str) -> _SQLExpectation:
        exp = _SQLExpectation("exec", pattern)
        self._expectations.append(exp)
        return exp

    def verify(self) -> None:
        unmet = [e.describe() for e in self._expectations
                 if not e.consumed]
        if unmet:
            raise ExpectationError(
                "sqlmock: expected statements never issued:\n  " +
                "\n  ".join(unmet))

    # ---- matching
    def _take(self, kind: str, sql: str, args: tuple) -> _SQLExpectation:
        self.statements.append((kind, sql, args))
        candidates = [e for e in self._expectations if not e.consumed]
        if self.ordered:
            candidates = candidates[:1]
        for exp in candidates:
            if exp.kind != kind or not exp.pattern.search(sql):
                continue
            if exp.args is not _ANY and tuple(exp.args) != tuple(args):
                continue
            exp.consumed = True
            if exp.exc is not None:
                raise exp.exc
            return exp
        nxt = next((e.describe() for e in self._expectations
                    if not e.consumed), "nothing")
        raise ExpectationError(
            f"sqlmock: unexpected {kind} {sql!r} args={args!r} "
            f"(next expected: {nxt})")

    # ---- the SQL surface
    def ph(self, n: int) -> str:
        return "?"

    def query(self, sql: str, *args: Any) -> list[dict]:
        return self._take("query", sql, args).rows

    def query_row(self, sql: str, *args: Any) -> dict | None:
        rows = self._take("query", sql, args).rows
        return rows[0] if rows else None

    def exec(self, sql: str, *args: Any) -> Any:
        # cursor-shaped result: handlers and auto-CRUD read .rowcount
        # off the real store's cursor (e.g. the 404-on-zero-rows path)
        return _ExecResult(self._take("exec", sql, args).rowcount)

    def select(self, entity_type: type, sql: str, *args: Any) -> list[Any]:
        rows = self._take("query", sql, args).rows
        import dataclasses
        if dataclasses.is_dataclass(entity_type):
            names = {f.name for f in dataclasses.fields(entity_type)}
            return [entity_type(**{k: v for k, v in r.items()
                                   if k in names}) for r in rows]
        return list(rows)

    def begin(self):
        from contextlib import contextmanager

        @contextmanager
        def tx():
            yield self
        return tx()

    def health_check(self) -> dict[str, Any]:
        return {"status": "UP", "details": {"dialect": "mock"}}

    def close(self) -> None:
        pass


class MockContainer(Container):
    def __init__(self, config: DictConfig | None = None) -> None:
        super().__init__(config=config or DictConfig(),
                         logger=MockLogger(level=DEBUG))
        self.register_framework_metrics()
        self.trace_exporter = InMemoryExporter()
        self.tracer = Tracer(service_name="mock-app", exporter=self.trace_exporter)
        self.mocks: dict[str, CallRecorder] = {}
        # real in-memory backends by default (sqlite SQL, dict KV,
        # in-process redis) so handler tests exercise actual query paths;
        # mock(slot) swaps any of them for a CallRecorder
        from ..datasource.kv import InMemoryKV
        from ..datasource.redis import Redis
        from ..datasource.sql import SQL
        self.add_sql(SQL(database=":memory:"))
        self.add_redis(Redis())
        self.add_kv_store(InMemoryKV())

    def mock(self, slot: str) -> CallRecorder:
        """Install a CallRecorder at a container slot and return it."""
        recorder = self.mocks.get(slot)
        if recorder is None:
            recorder = CallRecorder(slot)
            self.mocks[slot] = recorder
            setattr(self, slot, recorder)
        return recorder

    def mock_service(self, name: str) -> CallRecorder:
        recorder = CallRecorder(f"service:{name}")
        self.services[name] = recorder
        self.mocks[f"service:{name}"] = recorder
        return recorder

    def mock_sql(self, *, ordered: bool = True) -> SQLMock:
        """Swap container.sql for a sqlmock-style double (reference
        container/sql_mock.go:12); verify() covers it."""
        mock = SQLMock(ordered=ordered)
        self.sql = mock
        self.mocks["sql"] = mock  # type: ignore[assignment]
        return mock

    def verify(self) -> None:
        """Fail on any unmet expectation across every installed mock —
        the gomock-controller finish step. Call at test teardown (or
        use the container as a context manager)."""
        for recorder in self.mocks.values():
            recorder.verify()

    def __enter__(self) -> "MockContainer":
        return self

    def __exit__(self, exc_type, *_: Any) -> None:
        if exc_type is None:  # don't mask the test's own failure
            self.verify()

    @property
    def log_lines(self) -> list[dict]:
        return self.logger.lines  # type: ignore[attr-defined]


def new_mock_container() -> MockContainer:
    return MockContainer()
