"""The dependency-injection hub every handler Context carries.

The analog of the reference's ``Container`` (pkg/gofr/container/container.go:43-177):
one struct holding the logger, config, metrics manager, tracer,
registered inter-service HTTP clients, pub/sub client, datasources
(SQL/KV/file), and — the TPU-native addition with no reference
counterpart — the device registry + model runtimes served by this
process. ``Container.create`` wires everything from config the same
way ``container.Create`` does (env-driven, container.go:92-177).
"""

from __future__ import annotations

import time
from typing import Any

from ..config.env import DictConfig
from ..http.auth import TenantResolver
from ..logging.logger import Logger, level_from_string, new_logger
from ..metrics.registry import Manager as MetricsManager
from ..tracing.tracer import ConsoleExporter, InMemoryExporter, Tracer

STATUS_UP = "UP"
STATUS_DOWN = "DOWN"
STATUS_DEGRADED = "DEGRADED"

# 50µs–30s, the reference's datasource latency buckets
# (container/container.go:339-344)
_DATASOURCE_BUCKETS = (0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01,
                       0.05, 0.1, 0.5, 1, 5, 30)

# stores beyond the core set, each with a generated add_<slot> method
_BREADTH_SLOTS = ("mongo", "elasticsearch", "solr", "couchbase",
                  "cassandra", "scylladb", "clickhouse", "oracle",
                  "dgraph", "arangodb", "surrealdb", "opentsdb",
                  "influxdb", "dbresolver")

# every slot health() aggregates over and close() tears down
_DATASOURCE_SLOTS = ("sql", "redis", "kv", "file", "pubsub",
                     "tpu") + _BREADTH_SLOTS


class Container:
    def __init__(self, config=None, logger: Logger | None = None) -> None:
        self.config = config if config is not None else DictConfig()
        self.logger = logger if logger is not None else new_logger()
        self.app_name = "gofr-app"
        self.app_version = "dev"
        self.metrics: MetricsManager = MetricsManager(self.logger)
        self.tracer: Tracer = Tracer(service_name=self.app_name)
        # auth principal -> bounded accounting label; shared by the
        # request-log middleware and every serving handler so one
        # request resolves to ONE tenant everywhere
        self.tenant_resolver = TenantResolver()
        self.services: dict[str, Any] = {}   # name -> service.HTTPService
        self.pubsub: Any = None              # pubsub client
        self.sql: Any = None                 # SQL datasource
        self.redis: Any = None               # redis-shaped store
        self.kv: Any = None                  # key-value store
        self.file: Any = None                # file store
        self.ws_manager: Any = None          # websocket connection manager
        self.ws_services: dict[str, Any] = {}  # name -> outbound WSService
        self.extra_health: dict[str, Any] = {}  # name -> health_check()able
        # breadth datasource slots (reference container.go:43-75 holds one
        # field per store); _BREADTH_SLOTS is the single definition site —
        # it also drives the generated add_* methods, health() and close()
        for slot in _BREADTH_SLOTS:
            setattr(self, slot, None)
        self.tpu: Any = None                 # TPU device registry / runtime
        self.models: dict[str, Any] = {}     # name -> serving engine
        # stores with async connect (network brokers) wait here until an
        # event loop exists; App.start awaits connect_async()
        self._deferred_connects: list[Any] = []
        self._start_time = time.time()

    # ------------------------------------------------------------ factory
    @classmethod
    def create(cls, config) -> "Container":
        log_level = level_from_string(config.get_or_default("LOG_LEVEL", "INFO"))
        logger = new_logger(level=log_level)
        c = cls(config=config, logger=logger)
        c.app_name = config.get_or_default("APP_NAME", "gofr-app")
        c.app_version = config.get_or_default("APP_VERSION", "dev")

        c.metrics = MetricsManager(logger)
        c.register_framework_metrics()

        ratio = config.get_float("TRACER_RATIO", 1.0) if hasattr(config, "get_float") else 1.0
        exporter_kind = config.get_or_default("TRACE_EXPORTER", "none").lower()
        exporter = None
        if exporter_kind in ("console", "gofr"):
            exporter = ConsoleExporter(logger)
        elif exporter_kind == "memory":
            exporter = InMemoryExporter()
        elif exporter_kind in ("otlp", "jaeger", "zipkin"):
            # network exporters to a real collector by URL (reference
            # otel.go:131-151; jaeger accepts both protocols — use OTLP)
            url = config.get_or_default(
                "TRACER_URL", config.get_or_default(
                    "TRACER_HOST", "localhost"))
            if "://" not in url:
                port = config.get_or_default(
                    "TRACER_PORT", "9411" if exporter_kind == "zipkin"
                    else "4318")
                url = f"http://{url}:{port}"
            from ..tracing.export import OTLPHTTPExporter, ZipkinExporter
            cls = ZipkinExporter if exporter_kind == "zipkin" \
                else OTLPHTTPExporter
            exporter = cls(url, service_name=c.app_name, logger=logger)
        c.tracer = Tracer(service_name=c.app_name, exporter=exporter, ratio=ratio)

        # Env-driven datasources (reference container.go:128-174); anything
        # not configured stays None and can be attached later via add_*.
        from ..datasource.redis import new_redis
        from ..datasource.sql import new_sql
        c.sql = new_sql(config, logger, c.metrics, c.tracer)
        c.redis = new_redis(config, logger, c.metrics, c.tracer)

        # pub/sub backend switch (reference container.go:132-172 selects
        # KAFKA/GOOGLE/MQTT from PUBSUB_BACKEND; ours: KAFKA/GOOGLE/
        # EVENTHUB/NATS/JETSTREAM/MQTT/MEMORY)
        backend = config.get_or_default("PUBSUB_BACKEND", "").upper()
        if backend == "GOOGLE":
            from ..pubsub.google import GooglePubSubClient
            c.add_pubsub(GooglePubSubClient(
                endpoint=config.get_or_default("PUBSUB_BROKER",
                                               "127.0.0.1:8085"),
                project=config.get_or_default("GOOGLE_PROJECT_ID", "gofr")))
        elif backend == "EVENTHUB":
            from ..pubsub.eventhub import EventHubClient
            c.add_pubsub(EventHubClient(
                namespace=config.get_or_default("PUBSUB_BROKER",
                                                "127.0.0.1:9092"),
                eventhub=config.get_or_default("EVENTHUB_NAME", ""),
                consumer_group=config.get_or_default(
                    "KAFKA_CONSUMER_GROUP", "$Default")))
        elif backend == "KAFKA":
            from ..pubsub.kafka import KafkaClient
            c.add_pubsub(KafkaClient(
                brokers=config.get_or_default("PUBSUB_BROKER",
                                              "127.0.0.1:9092"),
                group_id=config.get_or_default("KAFKA_CONSUMER_GROUP",
                                               c.app_name),
                client_id=c.app_name,
                auto_offset=config.get_or_default(
                    "KAFKA_AUTO_OFFSET", "earliest").lower()))
        elif backend in ("NATS", "JETSTREAM"):
            addr = config.get_or_default("PUBSUB_BROKER", "127.0.0.1:4222")
            addr = addr.split("://", 1)[-1]  # tolerate nats:// scheme
            host, _, port_s = addr.rpartition(":")
            try:
                port = int(port_s)
            except ValueError:
                host, port = addr, 4222  # bare hostname, default port
            if backend == "JETSTREAM":
                from ..pubsub.jetstream import JetStreamClient
                c.add_pubsub(JetStreamClient(host or "127.0.0.1", port,
                                             name=c.app_name))
            else:
                from ..pubsub.nats import NATSClient
                c.add_pubsub(NATSClient(host or "127.0.0.1", port,
                                        name=c.app_name))
        elif backend == "MQTT":
            from ..pubsub.mqtt import MQTTClient
            try:
                qos = int(config.get_or_default("MQTT_QOS", "1"))
            except ValueError:
                qos = 1
            # the client implements QoS 0/1 (QoS 2 would wait for a
            # PUBACK that spec brokers answer with PUBREC)
            qos = min(max(qos, 0), 1)
            try:
                mqtt_port = int(config.get_or_default("MQTT_PORT",
                                                      "1883").strip())
            except ValueError:
                logger.error("invalid MQTT_PORT; using 1883")
                mqtt_port = 1883
            c.add_pubsub(MQTTClient(
                host=config.get_or_default("MQTT_HOST", "127.0.0.1"),
                port=mqtt_port,
                client_id=config.get_or_default("MQTT_CLIENT_ID", c.app_name),
                qos=qos))
        elif backend in ("MEMORY", "INMEMORY"):
            from ..pubsub.inmemory import InMemoryBroker
            c.add_pubsub(InMemoryBroker(logger=logger, metrics=c.metrics))
        return c

    # ------------------------------------------------- framework metrics
    def register_framework_metrics(self) -> None:
        """The standard metric set (reference container.go:252-284)."""
        m = self.metrics
        m.new_gauge("app_info", "static app info")
        m.set_gauge("app_info", 1, app_name=self.app_name, app_version=self.app_version)
        m.new_gauge("app_uptime_seconds", "seconds since boot")
        m.new_histogram("app_http_response", "http response time in seconds")
        m.new_histogram("app_http_service_response",
                        "outbound http client response time in seconds")
        m.new_histogram("app_sql_stats", "sql query time in seconds",
                        buckets=_DATASOURCE_BUCKETS)
        m.new_histogram("app_kv_stats", "kv op time in seconds",
                        buckets=_DATASOURCE_BUCKETS)
        m.new_histogram("app_nats_kv_stats",
                        "NATS JetStream KV op time in seconds",
                        buckets=_DATASOURCE_BUCKETS)
        m.new_histogram("app_redis_stats", "redis op time in seconds",
                        buckets=_DATASOURCE_BUCKETS)
        m.new_histogram("app_file_stats", "file op time in seconds",
                        buckets=_DATASOURCE_BUCKETS)
        m.new_histogram("app_pubsub_publish_latency", "publish time in seconds")
        m.new_counter("app_pubsub_publish_total_count", "messages published")
        m.new_counter("app_pubsub_publish_success_count", "publishes succeeded")
        m.new_counter("app_pubsub_subscribe_total_count", "messages received")
        m.new_counter("app_pubsub_subscribe_success_count", "messages handled")
        # TPU-native series (no reference counterpart)
        m.new_gauge("app_tpu_hbm_bytes_used", "HBM bytes in use per device")
        m.new_gauge("app_tpu_device_count", "visible TPU devices")
        m.new_histogram("app_tpu_execute_seconds", "device execute wall time",
                        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                                 0.05, 0.1, 0.25, 0.5, 1, 5))
        latency_buckets = (0.005, 0.01, 0.025, 0.05, 0.075, 0.1, 0.15,
                           0.25, 0.5, 1, 2, 5)
        m.new_histogram("app_chat_ttft_seconds", "time to first token",
                        buckets=latency_buckets)
        m.new_histogram("app_chat_queue_seconds",
                        "submit -> first slot assignment (admission "
                        "queue wait)", buckets=latency_buckets)
        m.new_histogram("app_chat_e2e_seconds",
                        "submit -> finish wall time",
                        buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1,
                                 2.5, 5, 10, 30, 60))
        m.new_histogram("app_chat_tpot_seconds",
                        "per-request mean inter-token latency",
                        buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01,
                                 0.025, 0.05, 0.1, 0.25, 0.5, 1))
        # fleet (multi-host control plane) series, written by
        # serving/control_plane.py on the leader; zero-valued on hosts
        # that never lead a serving group
        m.new_gauge("app_fleet_world_size",
                    "control-plane serving-group members")
        m.new_gauge("app_fleet_generation",
                    "control-plane membership generation")
        m.new_gauge("app_fleet_pass_skew",
                    "max/median p95 pass duration across hosts "
                    "(1 = balanced)")
        m.new_gauge("app_fleet_occupancy_skew",
                    "max/median mean batch occupancy across hosts")
        m.new_gauge("app_fleet_straggler_ratio",
                    "fraction of hosts whose p95 pass duration exceeds "
                    "straggler_ratio x the fleet median")
        m.new_gauge("app_fleet_goodput_ratio",
                    "fleet-wide useful device time over busy device "
                    "time, summed across member heartbeat goodput "
                    "digests")
        m.new_counter("app_fleet_evictions",
                      "hosts evicted from the serving group "
                      "(by reason label)")
        m.new_counter("app_fleet_heartbeats",
                      "control-plane heartbeats received")
        # leader-HA series (serving/control_plane.py): epoch fencing
        # and worker-driven failover — control-plane cadence only
        m.new_gauge("app_fleet_leader_epoch",
                    "this leader's election epoch (bumps on every "
                    "takeover; workers reject lower-epoch acks)")
        m.new_counter("app_fleet_failovers",
                      "worker failover rounds to a new leader "
                      "(by reason: missed_acks/stale_leader/"
                      "not_leader)")
        m.new_counter("app_fleet_stale_leader_rejects",
                      "control messages refused because they carried "
                      "a higher epoch than this leader holds (a "
                      "revived stale leader being fenced)")
        # output-integrity quarantine series (serving/control_plane.py
        # _vote_integrity): divergence-vote outcomes, control-plane
        # cadence only
        m.new_gauge("app_fleet_quarantined_hosts",
                    "hosts currently quarantined by the integrity "
                    "divergence vote (routed share held at zero until "
                    "they rejoin)")
        m.new_counter("app_fleet_quarantines",
                      "integrity-divergence quarantine actions "
                      "(by action label: quarantine/rejoin)")
        # tenant metering + SLO series, written by the usage ledger /
        # SLO tracker (serving/observability.py) at request retire;
        # tenant-labeled counters SUM across hosts under federation
        m.new_counter("app_tenant_requests",
                      "retired requests by tenant and status "
                      "(ok/error/cancelled)")
        m.new_counter("app_tenant_prompt_tokens",
                      "prompt tokens by tenant")
        m.new_counter("app_tenant_completion_tokens",
                      "generated tokens by tenant")
        m.new_counter("app_tenant_device_seconds",
                      "device busy time attributed to each tenant "
                      "(per-request share of every pass's busy span)")
        m.new_counter("app_tenant_waste_seconds",
                      "per-tenant attributable waste device time by "
                      "cause (preempt_recompute, spec_rejected)")
        m.new_histogram("app_tenant_queue_seconds",
                        "admission queue wait by tenant",
                        buckets=latency_buckets)
        m.new_histogram("app_tenant_e2e_seconds",
                        "submit -> finish wall time by tenant",
                        buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1,
                                 2.5, 5, 10, 30, 60))
        m.new_gauge("app_slo_burn_rate",
                    "error-budget burn rate by window (1 = spending "
                    "the budget at exactly the sustainable pace)")
        m.new_gauge("app_slo_error_budget_remaining",
                    "fraction of the availability error budget left "
                    "over SLOConfig.budget_window_s")
        # admission-scheduler series (serving/scheduler.py): written at
        # admission rejects / starvation preempts / the throttled gauge
        # pass — never from the decode hot loop
        m.new_gauge("app_sched_lane_depth",
                    "queued requests per scheduler lane "
                    "(interactive/background)")
        m.new_gauge("app_sched_tenant_share",
                    "per-tenant fraction of windowed device time "
                    "(the fair-share dequeue signal)")
        m.new_gauge("app_sched_shed_active",
                    "1 while a burn-rate shed episode is active")
        m.new_counter("app_sched_rejections",
                      "admission refusals by cause "
                      "(queue_full/rate_limited/shed) and tenant")
        m.new_counter("app_sched_preemptions",
                      "scheduler-initiated background preemptions to "
                      "unstarve the interactive lane")
        # fleet front-door series (serving/router.py): written by the
        # leader's data-plane router at route/retry/autoscale time —
        # leader-side host work, never on any worker's decode path
        m.new_gauge("app_router_routed_share",
                    "per-host fraction of requests the leader's "
                    "router forwarded")
        m.new_gauge("app_router_cache_hit_ratio",
                    "fraction of routed requests sent to a host whose "
                    "prefix digest covered part of the prompt")
        m.new_counter("app_router_routed",
                      "requests the fleet router forwarded to a "
                      "member (by host label)")
        m.new_counter("app_router_retries",
                      "router failovers to the next-best host on "
                      "typed retryable rejects or connect errors "
                      "(by code label)")
        m.new_counter("app_router_affinity_hits",
                      "requests routed by session affinity")
        m.new_counter("app_router_scale_decisions",
                      "autoscale decisions the router emitted "
                      "(by action label)")
        m.new_counter("app_router_client_aborts",
                      "proxied streams cancelled because the "
                      "downstream client disconnected mid-stream "
                      "(upstream slot released early)")
        # flight-data-recorder series (serving/events.py): written
        # wherever a state transition lands on the event ledger —
        # boundary/exception/control-plane code, never the hot loop
        m.new_counter("app_events_total",
                      "event-ledger records by kind "
                      "(the flight data recorder's emission rate)")
        m.new_counter("app_events_dropped",
                      "event-ledger ring evictions by kind — a "
                      "truncated timeline is visible, never silent")

    # ------------------------------------------------------------- health
    def health(self) -> dict[str, Any]:
        """Aggregate health over every attached capability
        (reference container/health.go:8-98)."""
        details: dict[str, Any] = {
            "name": self.app_name,
            "version": self.app_version,
            "uptime_seconds": round(time.time() - self._start_time, 1),
        }
        statuses: list[str] = []
        checks: dict[str, Any] = {}
        for name in _DATASOURCE_SLOTS:
            source = getattr(self, name)
            if source is None:
                continue
            checks[name] = self._check_one(source)
            statuses.append(checks[name].get("status", STATUS_DOWN))
        for svc_name, svc in self.services.items():
            checks[f"service:{svc_name}"] = self._check_one(svc)
            statuses.append(checks[f"service:{svc_name}"].get("status", STATUS_DOWN))
        for extra_name, source in self.extra_health.items():
            checks[extra_name] = self._check_one(source)
            statuses.append(checks[extra_name].get("status", STATUS_DOWN))
        status = STATUS_UP
        if any(s != STATUS_UP for s in statuses):
            status = STATUS_DEGRADED
        return {"status": status, "details": details, "checks": checks}

    def _check_one(self, source: Any) -> dict[str, Any]:
        import asyncio
        import inspect
        try:
            check = getattr(source, "health_check", None)
            if check is None:
                return {"status": STATUS_UP}
            result = check()
            if inspect.iscoroutine(result):
                # works from executor threads AND from inside a running
                # loop (async handlers): hop to a throwaway thread
                try:
                    asyncio.get_running_loop()
                except RuntimeError:
                    result = asyncio.run(result)
                else:
                    import concurrent.futures
                    # no `with`: shutdown(wait=True) would join a hung
                    # check and defeat the 10s bound
                    pool = concurrent.futures.ThreadPoolExecutor(1)
                    try:
                        result = pool.submit(asyncio.run, result).result(10)
                    finally:
                        pool.shutdown(wait=False)
            if isinstance(result, dict):
                return result
            return {"status": STATUS_UP if result else STATUS_DOWN}
        except Exception as exc:
            return {"status": STATUS_DOWN, "error": str(exc)}

    # ------------------------------------------------------ registration
    def _provide(self, store: Any) -> Any:
        """use_logger → use_metrics → use_tracer → connect → return,
        the provider wiring order of reference external_db.go."""
        for setter, dep in (("use_logger", self.logger),
                            ("use_metrics", self.metrics),
                            ("use_tracer", self.tracer)):
            fn = getattr(store, setter, None)
            if fn is not None:
                fn(dep)
        connect = getattr(store, "connect", None)
        if connect is not None:
            import inspect
            if inspect.iscoroutinefunction(connect):
                self._deferred_connects.append(store)
            else:
                connect()
        return store

    async def connect_async(self) -> None:
        """Await every deferred (async) connect; failures log and leave
        the store down (health reports it), matching the reference's
        retry-in-background stance rather than failing boot."""
        while self._deferred_connects:
            store = self._deferred_connects.pop(0)
            try:
                await store.connect()
            except Exception as exc:
                self.logger.error(
                    f"connect {type(store).__name__} failed: {exc!r}")

    def add_sql(self, store: Any) -> Any:
        self.sql = self._provide(store)
        return self.sql

    def add_redis(self, store: Any) -> Any:
        self.redis = self._provide(store)
        return self.redis

    def add_kv_store(self, store: Any) -> Any:
        self.kv = self._provide(store)
        return self.kv

    def add_file_store(self, store: Any) -> Any:
        self.file = self._provide(store)
        return self.file

    def add_pubsub(self, client: Any) -> Any:
        self.pubsub = self._provide(client)
        return self.pubsub

    def register_service(self, name: str, service: Any) -> None:
        self.services[name] = service

    def register_health_check(self, name: str, source: Any) -> None:
        """Attach any extra ``health_check()``-bearing component (e.g.
        the serving control plane) to the aggregate health surface."""
        self.extra_health[name] = source

    def register_ws_service(self, name: str, service: Any) -> None:
        self.ws_services[name] = service

    def get_ws_service(self, name: str) -> Any:
        return self.ws_services.get(name)

    def get_http_service(self, name: str) -> Any:
        return self.services.get(name)

    def add_model(self, name: str, engine: Any) -> None:
        self.models[name] = engine

    def get_model(self, name: str) -> Any:
        return self.models.get(name)

    async def close(self) -> None:
        for attr in _DATASOURCE_SLOTS:
            source = getattr(self, attr)
            closer = getattr(source, "close", None)
            if closer is None:
                continue
            try:
                result = closer()
                if hasattr(result, "__await__"):
                    await result
            except Exception as exc:
                self.logger.warn(f"closing {attr}: {exc}")
        # flush the trace exporter last: the spans of this shutdown are
        # the ones a crash-loop investigation needs
        exporter_close = getattr(getattr(self.tracer, "exporter", None),
                                 "close", None)
        if exporter_close is not None:
            try:
                exporter_close()
            except Exception as exc:
                self.logger.warn(f"closing trace exporter: {exc}")


def _make_adder(slot: str):
    def add(self: Container, store: Any) -> Any:
        setattr(self, slot, self._provide(store))
        return getattr(self, slot)
    add.__name__ = f"add_{slot}"
    add.__doc__ = (f"Attach a {slot} store: use_logger → use_metrics → "
                   f"use_tracer → connect (reference external_db.go).")
    return add


for _slot in _BREADTH_SLOTS:
    setattr(Container, f"add_{_slot}", _make_adder(_slot))
del _slot
