from .container import Container
from .mock import MockContainer, new_mock_container

__all__ = ["Container", "MockContainer", "new_mock_container"]
