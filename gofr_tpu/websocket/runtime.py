"""Server-side websocket runtime: upgrade handshake + message loop.

The glue between the HTTP server's upgrade hook and user handlers —
reference pkg/gofr/websocket.go:30-49 (App.WebSocket registers a GET
route whose handler loop calls the user Handler per message, with
``ctx.bind`` reading a frame) and middleware/web_socket.go:14-37
(upgrade + Manager registration keyed by Sec-WebSocket-Key).

Auth: installed auth providers run BEFORE the handshake, so protected
apps never serve anonymous websockets (the upgrade path cannot bypass
the middleware chain).
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Mapping

from ..http.auth import is_exempt, run_provider
from ..http.request import HTTPRequest, bind_dataclass
from .connection import WSConnection, WSMessage
from .frames import accept_key

# strong refs: the event loop only weakly references tasks, so
# per-connection loops must be anchored or GC can kill live sockets
_LOOP_TASKS: set[asyncio.Task] = set()


class WSRequest:
    """Request implementation wrapping one inbound frame: ``bind``
    parses the frame payload, params come from the upgrade request."""

    def __init__(self, upgrade: HTTPRequest, message: WSMessage,
                 path_params: Mapping[str, str]) -> None:
        self._upgrade = upgrade
        self.message = message
        self._path_params = dict(path_params)

    def param(self, key: str) -> str:
        return self._upgrade.param(key)

    def params(self, key: str) -> list[str]:
        return self._upgrade.params(key)

    def path_param(self, key: str) -> str:
        return self._path_params.get(key, "")

    def host_name(self) -> str:
        return self._upgrade.host_name()

    def header(self, key: str) -> str:
        return self._upgrade.header(key)

    def bind(self, target: Any = None) -> Any:
        """Frame payload -> str, parsed JSON, or bound dataclass."""
        if not self.message.is_text:
            return bytes(self.message.data)
        text = self.message.text()
        if target is str or (target is None and not _looks_like_json(text)):
            return text
        try:
            data = json.loads(text)
        except json.JSONDecodeError:
            if target is None:
                return text
            raise
        if target is None or not isinstance(target, type):
            return data
        import dataclasses
        if dataclasses.is_dataclass(target) and isinstance(data, Mapping):
            return bind_dataclass(data, target)
        return data


def _looks_like_json(text: str) -> bool:
    stripped = text.lstrip()
    return stripped[:1] in ("{", "[", '"') or stripped in ("true", "false",
                                                           "null") \
        or stripped[:1].isdigit() or stripped[:1] == "-"


def make_upgrade_handler(ws_router, container, auth_providers,
                         logger) -> Any:
    """Build the server's upgrade hook:
    async (request, reader, writer) -> took_over."""

    async def upgrade(request: HTTPRequest, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> bool:
        matched = ws_router.match("WS", request.path)
        if matched is None:
            return False  # not a WS route; normal chain answers
        if request.headers.get("upgrade", "").lower() != "websocket":
            return False
        key = request.headers.get("sec-websocket-key", "")
        if not key:
            return False  # malformed; GET route answers 400/426
        if request.headers.get("sec-websocket-version", "") != "13":
            # RFC 6455 4.2.2: advertise the version we speak
            writer.write(b"HTTP/1.1 426 Upgrade Required\r\n"
                         b"Sec-WebSocket-Version: 13\r\n"
                         b"Connection: close\r\n"
                         b"Content-Length: 0\r\n\r\n")
            await writer.drain()
            writer.close()
            return True

        # auth runs BEFORE the handshake (same provider semantics as the
        # middleware chain); on failure fall through to the normal chain,
        # which produces the 401
        if not is_exempt(request.path):
            for provider in auth_providers:
                if not await run_provider(provider, request):
                    return False

        route, path_params = matched
        headers = ["HTTP/1.1 101 Switching Protocols", "Upgrade: websocket",
                   "Connection: Upgrade",
                   f"Sec-WebSocket-Accept: {accept_key(key)}"]
        writer.write(("\r\n".join(headers) + "\r\n\r\n").encode())
        await writer.drain()

        conn = WSConnection(reader, writer, conn_id=key)
        if container.ws_manager is not None:
            container.ws_manager.add(key, conn)
        task = asyncio.ensure_future(_message_loop(
            route.handler, request, conn, path_params, container, logger))
        _LOOP_TASKS.add(task)
        task.add_done_callback(_LOOP_TASKS.discard)
        return True

    return upgrade


async def _message_loop(handler, upgrade_request: HTTPRequest,
                        conn: WSConnection, path_params, container,
                        logger) -> None:
    """Per-message handler dispatch (reference websocket.go:100-117)."""
    from ..context import Context
    try:
        while True:
            message = await conn.recv()
            if message is None:
                break
            ctx = Context(request=WSRequest(upgrade_request, message,
                                            path_params),
                          container=container)
            ctx._ws_conn = conn
            auth_info = getattr(upgrade_request, "auth_info", None)
            if auth_info:
                ctx.set_auth_info(auth_info)
            try:
                result = handler(ctx)
                if hasattr(result, "__await__"):
                    result = await result
                if result is not None:
                    await conn.send(result)
            except (ConnectionError, asyncio.CancelledError):
                raise
            except Exception as exc:  # handler panic: log, keep the conn
                logger.error(f"ws handler error on {upgrade_request.path}: "
                             f"{exc!r}")
                try:
                    await conn.send({"error": str(exc) or
                                     exc.__class__.__name__})
                except (ConnectionError, RuntimeError):
                    break
    except (ConnectionError, asyncio.CancelledError):
        pass
    finally:
        if container.ws_manager is not None:
            container.ws_manager.remove(conn.conn_id)
        if not conn.closed:
            await conn.close()
