"""Server-side websocket runtime: upgrade middleware + message loop.

The glue between the HTTP server and user handlers — reference
pkg/gofr/websocket.go:30-49 (App.WebSocket registers a route whose
handler loop calls the user Handler per message, with ``ctx.bind``
reading a frame) and middleware/web_socket.go:14-37 (upgrade + Manager
registration keyed by Sec-WebSocket-Key).

The upgrade is the INNERMOST middleware — exactly the reference's
ordering (http_server.go:36-41: trace → log → CORS → metrics → auth →
WS upgrade) — so every installed middleware, including user middleware
and auth, runs before the handshake. A successful handshake returns a
101 response marked ``hijacked``: the server then leaves the socket to
the message loop.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Mapping

from ..http.request import HTTPRequest, bind_dataclass
from ..http.responder import ResponseData
from .connection import WSConnection, WSMessage
from .frames import accept_key

# strong refs: the event loop only weakly references tasks, so
# per-connection loops must be anchored or GC can kill live sockets
_LOOP_TASKS: set[asyncio.Task] = set()


class WSRequest:
    """Request implementation wrapping one inbound frame: ``bind``
    parses the frame payload, params come from the upgrade request."""

    def __init__(self, upgrade: HTTPRequest, message: WSMessage,
                 path_params: Mapping[str, str]) -> None:
        self._upgrade = upgrade
        self.message = message
        self._path_params = dict(path_params)

    def param(self, key: str) -> str:
        return self._upgrade.param(key)

    def params(self, key: str) -> list[str]:
        return self._upgrade.params(key)

    def path_param(self, key: str) -> str:
        return self._path_params.get(key, "")

    def host_name(self) -> str:
        return self._upgrade.host_name()

    def header(self, key: str) -> str:
        return self._upgrade.header(key)

    def bind(self, target: Any = None) -> Any:
        """Frame payload -> str, parsed JSON, or bound dataclass."""
        if not self.message.is_text:
            return bytes(self.message.data)
        text = self.message.text()
        if target is str or (target is None and not _looks_like_json(text)):
            return text
        try:
            data = json.loads(text)
        except json.JSONDecodeError:
            if target is None:
                return text
            raise
        if target is None or not isinstance(target, type):
            return data
        import dataclasses
        if dataclasses.is_dataclass(target) and isinstance(data, Mapping):
            return bind_dataclass(data, target)
        return data


def _looks_like_json(text: str) -> bool:
    stripped = text.lstrip()
    return stripped[:1] in ("{", "[", '"') or stripped in ("true", "false",
                                                           "null") \
        or stripped[:1].isdigit() or stripped[:1] == "-"


def make_ws_middleware(ws_router, container, logger):
    """The innermost middleware: performs the RFC 6455 handshake for
    matching requests that made it through the rest of the chain."""

    def mw(next_handler):
        async def wrapped(request: HTTPRequest) -> ResponseData:
            if request.headers.get("upgrade", "").lower() != "websocket":
                return await next_handler(request)
            matched = ws_router.match("WS", request.path)
            if matched is None:
                return await next_handler(request)
            writer = getattr(request, "ws_writer", None)
            reader = getattr(request, "ws_reader", None)
            if writer is None or reader is None:
                # transport that can't hand over the socket (tests
                # calling the chain directly): plain route answers
                return await next_handler(request)

            key = request.headers.get("sec-websocket-key", "")
            if not key:
                return await next_handler(request)  # route answers 426
            if request.headers.get("sec-websocket-version", "") != "13":
                # RFC 6455 4.2.2: advertise the version we speak
                return ResponseData(
                    status=426, body=b"",
                    headers={"Sec-WebSocket-Version": "13"})

            route, path_params = matched
            headers = ["HTTP/1.1 101 Switching Protocols",
                       "Upgrade: websocket", "Connection: Upgrade",
                       f"Sec-WebSocket-Accept: {accept_key(key)}"]
            writer.write(("\r\n".join(headers) + "\r\n\r\n").encode())
            await writer.drain()

            conn = WSConnection(reader, writer, conn_id=key)
            if container.ws_manager is not None:
                container.ws_manager.add(key, conn)
            task = asyncio.ensure_future(_message_loop(
                route.handler, request, conn, path_params, container,
                logger))
            _LOOP_TASKS.add(task)
            task.add_done_callback(_LOOP_TASKS.discard)

            response = ResponseData(status=101, body=b"")
            response.hijacked = True  # server: don't write, don't close
            return response
        return wrapped
    return mw


async def _message_loop(handler, upgrade_request: HTTPRequest,
                        conn: WSConnection, path_params, container,
                        logger) -> None:
    """Per-message handler dispatch (reference websocket.go:100-117)."""
    from ..context import Context
    try:
        while True:
            message = await conn.recv()
            if message is None:
                break
            ctx = Context(request=WSRequest(upgrade_request, message,
                                            path_params),
                          container=container)
            ctx._ws_conn = conn
            auth_info = getattr(upgrade_request, "auth_info", None)
            if auth_info:
                ctx.set_auth_info(auth_info)
            try:
                result = handler(ctx)
                if hasattr(result, "__await__"):
                    result = await result
                if result is not None:
                    await conn.send(result)
            except (ConnectionError, asyncio.CancelledError):
                raise
            except Exception as exc:  # panic recovery: keep the conn
                logger.error(f"ws handler error on {upgrade_request.path}: "
                             f"{exc!r}")
                # mirror the HTTP panic policy (handler.go:141): only
                # errors that declare a status/message are client-visible
                if hasattr(exc, "status_code"):
                    visible = str(exc) or exc.__class__.__name__
                else:
                    visible = "internal server error"
                try:
                    await conn.send({"error": visible})
                except (ConnectionError, RuntimeError):
                    break
    except (ConnectionError, asyncio.CancelledError):
        pass
    finally:
        if container.ws_manager is not None:
            container.ws_manager.remove(conn.conn_id)
        if not conn.closed:
            await conn.close()
