"""WSConnection: message-level API over the frame codec.

The per-connection object the framework hands to handlers (via
``ctx.write_message_to_socket``) and registers in the Manager —
reference pkg/gofr/websocket/websocket.go Connection. Handles
fragmentation reassembly, ping/pong, and the close handshake; one
writer lock serializes concurrent sends.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass
from typing import Any

from .frames import (
    CLOSE_NORMAL,
    CLOSE_PROTOCOL_ERROR,
    OP_BINARY,
    OP_CLOSE,
    OP_CONT,
    OP_PING,
    OP_PONG,
    OP_TEXT,
    MAX_FRAME_BYTES,
    WSProtocolError,
    close_payload,
    encode_frame,
    parse_close,
    read_frame,
)


@dataclass
class WSMessage:
    data: bytes
    is_text: bool

    def text(self) -> str:
        return self.data.decode("utf-8", "replace")


class WSConnection:
    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, *, is_client: bool = False,
                 conn_id: str = "") -> None:
        self.reader = reader
        self.writer = writer
        self.is_client = is_client  # clients mask, servers don't
        self.conn_id = conn_id
        self.closed = False
        self.close_code: int | None = None
        self._send_lock = asyncio.Lock()

    # ---------------------------------------------------------------- send
    async def _send_frame(self, opcode: int, payload: bytes) -> None:
        async with self._send_lock:
            self.writer.write(encode_frame(opcode, payload,
                                           mask=self.is_client))
            await self.writer.drain()

    async def send(self, data: Any) -> None:
        """str -> text frame; bytes -> binary; anything else -> JSON text."""
        if isinstance(data, (bytes, bytearray)):
            await self._send_frame(OP_BINARY, bytes(data))
        elif isinstance(data, str):
            await self._send_frame(OP_TEXT, data.encode())
        else:
            await self._send_frame(OP_TEXT, json.dumps(data).encode())

    async def ping(self, payload: bytes = b"") -> None:
        await self._send_frame(OP_PING, payload)

    async def close(self, code: int = CLOSE_NORMAL, reason: str = "") -> None:
        if self.closed:
            return
        self.closed = True
        self.close_code = code
        try:
            await self._send_frame(OP_CLOSE, close_payload(code, reason))
        except (ConnectionError, RuntimeError):
            pass
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except (ConnectionError, RuntimeError):
            pass

    # ---------------------------------------------------------------- recv
    async def recv(self) -> WSMessage | None:
        """Next data message; None once the connection is closed.

        Control frames are handled inline: pings answered, close echoed.
        Fragmented messages are reassembled.
        """
        buffer = bytearray()
        first_opcode: int | None = None
        while True:
            if self.closed:
                return None
            try:
                frame = await read_frame(self.reader,
                                         require_mask=not self.is_client)
            except WSProtocolError as exc:
                await self.close(exc.code, str(exc))
                return None
            if frame is None:  # EOF
                self.closed = True
                return None

            if frame.opcode == OP_CLOSE:
                code, _reason = parse_close(frame.payload)
                self.close_code = code
                if not self.closed:
                    self.closed = True
                    try:
                        await self._send_frame(OP_CLOSE,
                                               close_payload(code))
                    except (ConnectionError, RuntimeError):
                        pass
                    try:
                        self.writer.close()
                    except RuntimeError:
                        pass
                return None
            if frame.opcode == OP_PING:
                try:
                    await self._send_frame(OP_PONG, frame.payload)
                except (ConnectionError, RuntimeError):
                    pass
                continue
            if frame.opcode == OP_PONG:
                continue

            if frame.opcode in (OP_TEXT, OP_BINARY):
                if first_opcode is not None:
                    await self.close(CLOSE_PROTOCOL_ERROR,
                                     "interleaved data frames")
                    return None
                first_opcode = frame.opcode
            elif frame.opcode == OP_CONT:
                if first_opcode is None:
                    await self.close(CLOSE_PROTOCOL_ERROR,
                                     "orphan continuation")
                    return None
            else:
                await self.close(CLOSE_PROTOCOL_ERROR,
                                 f"unknown opcode {frame.opcode}")
                return None

            buffer += frame.payload
            if len(buffer) > MAX_FRAME_BYTES:
                await self.close(1009, "message too large")
                return None
            if frame.fin:
                return WSMessage(data=bytes(buffer),
                                 is_text=first_opcode == OP_TEXT)
