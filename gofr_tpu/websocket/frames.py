"""RFC 6455 frame codec: encode/decode over asyncio streams.

The wire layer under the framework's websocket support — the role
gorilla/websocket's framing plays for the reference
(pkg/gofr/websocket/). Server-to-client frames are unmasked,
client-to-server frames are masked, as the RFC requires.
"""

from __future__ import annotations

import asyncio
import os
import struct
from dataclasses import dataclass

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

CONTROL_OPS = (OP_CLOSE, OP_PING, OP_PONG)

CLOSE_NORMAL = 1000
CLOSE_GOING_AWAY = 1001
CLOSE_PROTOCOL_ERROR = 1002
CLOSE_UNSUPPORTED = 1003
CLOSE_TOO_LARGE = 1009
CLOSE_INTERNAL = 1011

MAX_FRAME_BYTES = 16 * 1024 * 1024

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def accept_key(key: str) -> str:
    """Sec-WebSocket-Accept derivation, shared by server and client."""
    import base64
    import hashlib
    return base64.b64encode(
        hashlib.sha1((key + WS_GUID).encode()).digest()).decode()


class WSProtocolError(Exception):
    def __init__(self, message: str, code: int = CLOSE_PROTOCOL_ERROR) -> None:
        super().__init__(message)
        self.code = code


@dataclass
class Frame:
    opcode: int
    payload: bytes
    fin: bool = True


def apply_mask(data: bytes, key: bytes) -> bytes:
    """XOR-mask via one big-int op (~100x faster than a per-byte loop;
    frames can be 16 MB and this runs on the event-loop thread)."""
    if not data:
        return data
    n = len(data)
    full_key = (key * ((n + 3) // 4))[:n]
    return (int.from_bytes(data, "little")
            ^ int.from_bytes(full_key, "little")).to_bytes(n, "little")


def encode_frame(opcode: int, payload: bytes, *, fin: bool = True,
                 mask: bool = False) -> bytes:
    head = bytearray()
    head.append((0x80 if fin else 0) | opcode)
    length = len(payload)
    mask_bit = 0x80 if mask else 0
    if length < 126:
        head.append(mask_bit | length)
    elif length < 1 << 16:
        head.append(mask_bit | 126)
        head += struct.pack(">H", length)
    else:
        head.append(mask_bit | 127)
        head += struct.pack(">Q", length)
    if mask:
        key = os.urandom(4)
        head += key
        payload = apply_mask(payload, key)
    return bytes(head) + payload


async def read_frame(reader: asyncio.StreamReader, *,
                     require_mask: bool) -> Frame | None:
    """Read one frame; None on clean EOF at a frame boundary."""
    try:
        head = await reader.readexactly(2)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    fin = bool(head[0] & 0x80)
    if head[0] & 0x70:
        raise WSProtocolError("nonzero RSV bits")
    opcode = head[0] & 0x0F
    masked = bool(head[1] & 0x80)
    length = head[1] & 0x7F

    if opcode in CONTROL_OPS and (not fin or length > 125):
        raise WSProtocolError("fragmented or oversized control frame")
    if masked != require_mask:
        raise WSProtocolError(
            "client frames must be masked" if require_mask
            else "server frames must not be masked")

    try:
        if length == 126:
            length = struct.unpack(">H", await reader.readexactly(2))[0]
        elif length == 127:
            length = struct.unpack(">Q", await reader.readexactly(8))[0]
        if length > MAX_FRAME_BYTES:
            raise WSProtocolError("frame too large", CLOSE_TOO_LARGE)
        key = await reader.readexactly(4) if masked else b""
        payload = await reader.readexactly(length) if length else b""
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    if masked and payload:
        payload = apply_mask(payload, key)
    return Frame(opcode=opcode, payload=payload, fin=fin)


def close_payload(code: int, reason: str = "") -> bytes:
    return struct.pack(">H", code) + reason.encode()[:123]


def parse_close(payload: bytes) -> tuple[int, str]:
    if len(payload) < 2:
        return CLOSE_NORMAL, ""
    code = struct.unpack(">H", payload[:2])[0]
    return code, payload[2:].decode("utf-8", "replace")
