"""Websocket support: RFC 6455 codec, connections, manager, outbound
services, and the server-side upgrade runtime."""

from .connection import WSConnection, WSMessage
from .frames import WSProtocolError
from .manager import WSManager
from .service import WSHandshakeError, WSService, connect

__all__ = ["WSConnection", "WSMessage", "WSManager", "WSService",
           "WSProtocolError", "WSHandshakeError", "connect"]
