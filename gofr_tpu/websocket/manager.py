"""Connection manager: live server-side connections keyed by
``Sec-WebSocket-Key`` (reference pkg/gofr/websocket/websocket.go
Manager + middleware/web_socket.go:14-37 registration)."""

from __future__ import annotations

import asyncio
from typing import Any, Iterable

from .connection import WSConnection


class WSManager:
    SEND_TIMEOUT = 5.0

    def __init__(self) -> None:
        self._connections: dict[str, WSConnection] = {}
        self._serial = 0

    def add(self, key: str, conn: WSConnection) -> str:
        """Register; returns the key actually used. The client-supplied
        Sec-WebSocket-Key is attacker-controlled, so duplicates get a
        server-side suffix instead of evicting the existing entry."""
        if key in self._connections:
            self._serial += 1
            key = f"{key}#{self._serial}"
            conn.conn_id = key
        self._connections[key] = conn
        return key

    def remove(self, key: str) -> None:
        self._connections.pop(key, None)

    def connection(self, key: str) -> WSConnection | None:
        return self._connections.get(key)

    def keys(self) -> list[str]:
        return list(self._connections)

    def __len__(self) -> int:
        return len(self._connections)

    async def send_to(self, key: str, data: Any) -> bool:
        conn = self._connections.get(key)
        if conn is None or conn.closed:
            return False
        await conn.send(data)
        return True

    async def broadcast(self, data: Any,
                        exclude: Iterable[str] = ()) -> int:
        """Concurrent best-effort fan-out with a per-connection timeout
        (one stalled client must not block the rest); returns the number
        of sends that worked."""
        skip = set(exclude)
        targets = [conn for key, conn in list(self._connections.items())
                   if key not in skip and not conn.closed]

        async def one(conn: WSConnection) -> bool:
            # CancelledError deliberately NOT caught: cancelling the
            # broadcasting task must unwind it, not be counted as a miss
            try:
                await asyncio.wait_for(conn.send(data), self.SEND_TIMEOUT)
                return True
            except (ConnectionError, RuntimeError, asyncio.TimeoutError):
                return False

        results = await asyncio.gather(*(one(c) for c in targets))
        return sum(results)

    async def close_all(self) -> None:
        async def one(conn: WSConnection) -> None:
            try:
                await asyncio.wait_for(
                    conn.close(1001, "server shutting down"),
                    self.SEND_TIMEOUT)
            except (ConnectionError, RuntimeError, asyncio.TimeoutError):
                pass
        await asyncio.gather(*(one(c)
                               for c in list(self._connections.values())))
        self._connections.clear()
