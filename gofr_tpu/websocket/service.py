"""Outbound websocket connections: client handshake + reconnecting
service (reference pkg/gofr/websocket.go:52-98 AddWSService)."""

from __future__ import annotations

import asyncio
import base64
import os
from typing import Any, Awaitable, Callable
from urllib.parse import urlsplit

from .connection import WSConnection, WSMessage
from .frames import accept_key


class WSHandshakeError(Exception):
    pass


async def connect(url: str, *, headers: dict[str, str] | None = None,
                  timeout: float = 10.0) -> WSConnection:
    """Open a client websocket connection (RFC 6455 opening handshake)."""
    split = urlsplit(url)
    if split.scheme not in ("ws", "wss"):
        raise WSHandshakeError(f"unsupported scheme {split.scheme!r}")
    host = split.hostname or "localhost"
    port = split.port or (443 if split.scheme == "wss" else 80)
    path = split.path or "/"
    if split.query:
        path += "?" + split.query

    ssl_ctx = None
    if split.scheme == "wss":
        import ssl
        ssl_ctx = ssl.create_default_context()
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port, ssl=ssl_ctx), timeout)

    key = base64.b64encode(os.urandom(16)).decode()
    try:
        lines = [f"GET {path} HTTP/1.1", f"Host: {host}:{port}",
                 "Upgrade: websocket", "Connection: Upgrade",
                 f"Sec-WebSocket-Key: {key}", "Sec-WebSocket-Version: 13"]
        for k, v in (headers or {}).items():
            lines.append(f"{k}: {v}")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode())
        await writer.drain()

        head = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), timeout)
        response_lines = head.decode("latin-1").split("\r\n")
        status_parts = response_lines[0].split(" ", 2)
        if len(status_parts) < 2 or status_parts[1] != "101":
            raise WSHandshakeError(
                f"handshake rejected: {response_lines[0]}")
        response_headers = {}
        for line in response_lines[1:]:
            if ":" in line:
                k, _, v = line.partition(":")
                response_headers[k.strip().lower()] = v.strip()
        if response_headers.get("sec-websocket-accept") != accept_key(key):
            raise WSHandshakeError("bad Sec-WebSocket-Accept")
    except BaseException:  # incl. TimeoutError: never leak the socket
        writer.close()
        raise
    return WSConnection(reader, writer, is_client=True, conn_id=key)


class WSService:
    """A named outbound connection that reconnects with backoff.

    ``send`` raises ConnectionError while disconnected; an optional
    ``on_message`` callback receives inbound messages.
    """

    def __init__(self, name: str, url: str, *,
                 headers: dict[str, str] | None = None,
                 retry_interval: float = 5.0, logger: Any = None,
                 on_message: Callable[[WSMessage], Awaitable[None] | None] | None = None) -> None:
        self.name = name
        self.url = url
        self.headers = headers
        self.retry_interval = retry_interval
        self.logger = logger
        self.on_message = on_message
        self.conn: WSConnection | None = None
        self._task: asyncio.Task | None = None
        self._stopped = False
        self._connected = asyncio.Event()

    @property
    def connected(self) -> bool:
        return self.conn is not None and not self.conn.closed

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._maintain())

    async def wait_connected(self, timeout: float = 10.0) -> bool:
        try:
            await asyncio.wait_for(self._connected.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def _maintain(self) -> None:
        """Connect; on drop, retry every ``retry_interval``
        (reference websocket.go:77-98)."""
        while not self._stopped:
            try:
                self.conn = await connect(self.url, headers=self.headers)
                self._connected.set()
                if self.logger:
                    self.logger.info(f"ws service {self.name}: connected")
                while not self._stopped:
                    message = await self.conn.recv()
                    if message is None:
                        break
                    if self.on_message is not None:
                        result = self.on_message(message)
                        if result is not None and hasattr(result, "__await__"):
                            await result
            except asyncio.CancelledError:
                return
            except Exception as exc:
                if self.logger:
                    self.logger.warn(f"ws service {self.name}: {exc!r}")
            self._connected.clear()
            if self.conn is not None:  # release the old transport
                try:
                    await self.conn.close(1001, "reconnecting")
                except (ConnectionError, RuntimeError):
                    pass
                self.conn = None
            if self._stopped:
                return
            await asyncio.sleep(self.retry_interval)

    async def send(self, data: Any) -> None:
        if not self.connected:
            raise ConnectionError(f"ws service {self.name} not connected")
        assert self.conn is not None
        await self.conn.send(data)

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
        if self.conn is not None:
            await self.conn.close(1001, "client shutting down")
            self.conn = None
