"""Sharded training step: dp + tp + sp (+ep for MoE) in one jit.

The GSPMD path: parameters are placed with Megatron-style specs
(sharding.py), the batch is sharded over ``dp``, activations get
sequence-parallel constraints over the ``tp`` axis between blocks, and
XLA inserts the gradient psum / all-gather / reduce-scatter on ICI.
Pipeline (``pp``) meshes route through :mod:`.pipeline`'s GPipe runner
instead (``make_train_step`` dispatches).

Optimizer state inherits the parameter shardings (same pytree
structure), so Adam moments are fully distributed — ZeRO-style — for
free.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, llama_init, llama_prefill
from .mesh import mesh_axes
from .sharding import llama_param_specs, shard_params


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step), None),
    lambda _, c: TrainState(params=c[0], opt_state=c[1], step=c[2]))


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray,
                       mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Mean next-token CE. logits [B,S,V] f32, targets [B,S] int."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        mask = mask.astype(jnp.float32)
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return nll.mean()


def default_optimizer(learning_rate: float = 3e-4) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=0.1),
    )


def make_train_state(key: jax.Array, config: LlamaConfig, mesh: Mesh, *,
                     optimizer: optax.GradientTransformation | None = None,
                     init_fn: Callable = llama_init,
                     specs_fn: Callable = llama_param_specs) -> tuple[TrainState, Any]:
    """Init + shard params and optimizer state over the mesh."""
    optimizer = optimizer or default_optimizer()
    specs = specs_fn(mesh)
    params = init_fn(key, config)
    params = shard_params(params, mesh, specs)
    opt_state = optimizer.init(params)  # moments inherit param shardings
    step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
    return TrainState(params=params, opt_state=opt_state, step=step), optimizer


def make_train_step(config: LlamaConfig, mesh: Mesh, *,
                    optimizer: optax.GradientTransformation | None = None,
                    forward_fn: Callable | None = None,
                    donate: bool = True) -> Callable:
    """Build the jitted full train step for a dense model on a dp/tp/sp
    mesh. For pipeline meshes (pp>1) use pipeline.make_pipeline_train_step.
    """
    axes = mesh_axes(mesh)
    if axes.get("pp", 1) > 1:
        if forward_fn is not None:
            raise ValueError(
                "pipeline meshes run the built-in llama stage forward; "
                "custom forward_fn is only supported on dense meshes")
        from .pipeline import make_pipeline_train_step
        return make_pipeline_train_step(config, mesh, optimizer=optimizer,
                                        donate=donate)

    optimizer = optimizer or default_optimizer()
    tp = "tp" if "tp" in axes else None
    dp = "dp" if "dp" in axes else None

    def constrain(x):
        # Megatron sequence parallel: residual activations sharded
        # [batch over dp, sequence over tp]
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(dp, tp, None)))

    fwd = forward_fn or (lambda params, tokens: llama_prefill(
        params, tokens, config, implementation="xla", constrain=constrain)[0])

    def loss_fn(params, tokens, targets, mask):
        logits = fwd(params, tokens)
        return cross_entropy_loss(logits, targets, mask)

    def train_step(state: TrainState, tokens, targets, mask):
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, tokens, targets, mask)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params=params, opt_state=opt_state,
                          step=state.step + 1), loss

    batch_sharding = NamedSharding(mesh, P(dp, None))
    return jax.jit(
        train_step,
        in_shardings=(None, batch_sharding, batch_sharding, batch_sharding),
        donate_argnums=(0,) if donate else ())
