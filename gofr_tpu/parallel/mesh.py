"""Device mesh construction for ICI-aware multi-chip execution.

The TPU-native replacement for the reference's horizontal scale-out
(stateless replicas behind brokers, SURVEY §2.9): scale comes from a
``jax.sharding.Mesh`` whose axes map onto ICI rings, with XLA inserting
the collectives. Axis conventions across the framework:

- ``dp``: data parallel (batch dim; gradient psum)
- ``pp``: pipeline parallel (layer stages; ppermute activations)
- ``tp``: tensor parallel (hidden/head dims; all-gather/reduce-scatter)
- ``sp``: sequence parallel for long context (ring attention); when a
  mesh has no dedicated ``sp`` axis, sequence sharding rides ``tp``
  (Megatron-style) via sharding constraints.
- ``ep``: expert parallel (MoE expert dim)

``create_mesh({"dp": 2, "tp": 4})`` uses all visible devices; sizes
must multiply to the device count (a trailing -1 axis is inferred).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def mesh_axes(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` with replication checking off, across the jax
    rename: new jax exposes ``jax.shard_map(..., check_vma=False)``,
    older toolchains ``jax.experimental.shard_map.shard_map(...,
    check_rep=False)``."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def create_mesh(axes: dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {axis: size}; one size may be -1 (inferred)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axes)
    unknown = [k for k, v in sizes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError("at most one axis size may be -1")
    known = math.prod(v for v in sizes.values() if v != -1)
    if unknown:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[unknown[0]] = n // known
    need = math.prod(sizes.values())
    if need > n:
        raise ValueError(f"mesh {sizes} needs {need} devices, have {n}")
    # a fully-specified smaller mesh uses the first `need` devices
    grid = np.array(devices[:need]).reshape(*sizes.values())
    return Mesh(grid, tuple(sizes.keys()))


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def local_slice_size(mesh: Mesh, axis: str, dim: int) -> int:
    size = mesh_axes(mesh).get(axis, 1)
    if dim % size:
        raise ValueError(f"dim {dim} not divisible by {axis}={size}")
    return dim // size
