"""Ring attention: causal attention over sequence-sharded q/k/v.

Long-context path (SURVEY §5 "long-context obligation"): the sequence
axis is sharded over the ``sp`` mesh axis; each device holds a
contiguous sequence chunk and K/V blocks rotate around the ring with
``lax.ppermute`` while a running online-softmax accumulator merges
partial results — attention over sequences far beyond one chip's VMEM/
HBM without ever materializing the full [S, S] score matrix on one
device.

Causality across chunks: with chunk index ``r`` (this device) and the
k/v chunk currently held originating from device ``src``, the block is
- fully visible when ``src < r`` (entirely in the past),
- causal-diagonal when ``src == r``,
- fully masked when ``src > r`` (entirely in the future) — skipped by
  zero-weighting, keeping the loop shape static.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, scale, row_off, col_off, mode):
    """Partial attention of q against one k/v block with running-softmax
    stats. mode: 0 full, 1 diagonal-causal, 2 masked."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    sq, skv = q.shape[1], k.shape[1]
    row = row_off + jnp.arange(sq)
    col = col_off + jnp.arange(skv)
    causal = col[None, :] <= row[:, None]
    mask = jnp.where(mode == 2, False,
                     jnp.where(mode == 1, causal, True))
    s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [b,h,q]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o, m, l


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   axis_name: str = "sp",
                   scale: float | None = None) -> jnp.ndarray:
    """Causal attention inside shard_map: q/k/v [B, S_local, H, D] are
    this device's sequence chunk; returns the local output chunk."""
    # axis_size is the newer spelling; psum(1, axis) constant-folds to
    # the same static int on toolchains that predate it
    ring = (int(jax.lax.axis_size(axis_name))
            if hasattr(jax.lax, "axis_size")
            else int(jax.lax.psum(1, axis_name)))
    rank = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5

    acc = jnp.zeros((b, h, s_local, d), jnp.float32)
    m_run = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l_run = jnp.zeros((b, h, s_local), jnp.float32)

    row_off = rank * s_local
    k_cur, v_cur = k, v
    src = rank  # origin of the k/v chunk currently held

    for step in range(ring):
        mode = jnp.where(src == rank, 1, jnp.where(src < rank, 0, 2))
        col_off = src * s_local
        o_blk, m_blk, l_blk = _block_attend(q, k_cur, v_cur, scale,
                                            row_off, col_off, mode)
        o_blk = jnp.moveaxis(o_blk, 1, 2)  # [b,q,h,d] -> [b,h,q,d]
        m_new = jnp.maximum(m_run, m_blk)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(m_blk - m_new)
        acc = acc * alpha[..., None] + o_blk * beta[..., None]
        l_run = l_run * alpha + l_blk * beta
        m_run = m_new
        if step < ring - 1:
            # rotate k/v to the next device; origin index rotates with it
            perm = [(i, (i + 1) % ring) for i in range(ring)]
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            src = jax.lax.ppermute(src, axis_name, perm)

    out = acc / jnp.maximum(l_run, 1e-30)[..., None]  # [b,h,q,d]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)    # [b,q,h,d]


def make_ring_attention(mesh: Mesh, axis_name: str = "sp"):
    """Jitted sequence-sharded causal attention over the mesh.

    Takes global [B, S, H, D] arrays (sequence sharded over
    ``axis_name``) and returns the same layout.
    """
    spec = P(None, axis_name, None, None)

    from .mesh import shard_map_compat
    fn = shard_map_compat(
        functools.partial(ring_attention, axis_name=axis_name),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)

    def apply(q, k, v):
        sharding = NamedSharding(mesh, spec)
        q = jax.lax.with_sharding_constraint(q, sharding)
        k = jax.lax.with_sharding_constraint(k, sharding)
        v = jax.lax.with_sharding_constraint(v, sharding)
        return fn(q, k, v)

    return jax.jit(apply)
