"""Pipeline-parallel (GPipe) training step via shard_map + ppermute.

Layer stages live on the ``pp`` mesh axis (the stacked ``[L, ...]``
weights shard their leading axis, sharding.py), activations flow
stage-to-stage over ICI with ``lax.ppermute``, microbatches fill the
pipeline GPipe-style: with ``P`` stages and ``M`` microbatches the loop
runs ``M + P - 1`` ticks and every stage is busy in the steady state.
Data parallel composes manually inside the same shard_map (gradient
psum over ``dp``).

Differentiating straight through the shard_map gives the backward
pipeline for free (jax ADs ppermute into the reverse permute).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, _attn_block, _logits, _mlp_block
from ..ops.rope import rope_frequencies
from .mesh import mesh_axes
from .train import TrainState, cross_entropy_loss, default_optimizer


def _stage_forward(x, layers_local, c: LlamaConfig, inv_freq, positions):
    """Run this stage's slice of layers over activations x [mb, S, D].

    Reuses the dense path's block math (models/llama.py) so pipeline
    stages can never drift from single-chip semantics."""

    def layer_fn(x, lp):
        out, _k, _v = _attn_block(x, lp, c, inv_freq, positions, None, "xla")
        x = x + out
        return x + _mlp_block(x, lp, c), None

    x, _ = jax.lax.scan(layer_fn, x, layers_local)
    return x


def make_pipeline_train_step(config: LlamaConfig, mesh: Mesh, *,
                             optimizer: optax.GradientTransformation | None = None,
                             num_microbatches: int | None = None,
                             donate: bool = True) -> Callable:
    """GPipe train step for a ('dp','pp') mesh.

    Batch layout: tokens/targets/mask [M, mb, S] where M = microbatches
    (defaults to the pp size) and mb is the per-dp-shard microbatch.
    """
    axes = mesh_axes(mesh)
    pp = axes.get("pp", 1)
    if axes.get("tp", 1) != 1:
        raise ValueError("pipeline step composes with dp only; use the "
                         "dense GSPMD step for tp/sp meshes")
    M = num_microbatches or pp
    if M < pp:
        raise ValueError(f"need at least {pp} microbatches to fill the pipe")
    optimizer = optimizer or default_optimizer()
    c = config

    def pipe_loss(params, tokens, targets, mask):
        """Runs per (dp, pp) shard: tokens [M, mb, S] local to this dp shard."""
        stage = jax.lax.axis_index("pp")
        inv_freq = rope_frequencies(c.head_dim, c.rope_theta, c.rope_scaling)
        mb, s = tokens.shape[1], tokens.shape[2]
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (mb, s))
        layers_local = params["layers"]

        def embed(tok):
            return params["embed"][tok]

        def head_loss(x, tgt, msk):
            logits = _logits(params, c, x)
            nll = -jnp.take_along_axis(
                jax.nn.log_softmax(logits, axis=-1), tgt[..., None],
                axis=-1)[..., 0]
            mskf = msk.astype(jnp.float32)
            return (nll * mskf).sum(), mskf.sum()

        carry = jnp.zeros((mb, s, c.dim), c.dtype)
        loss_sum = jnp.zeros((), jnp.float32)
        count_sum = jnp.zeros((), jnp.float32)
        # M + pp - 1 pipeline ticks (python loop: static unroll)
        for t in range(M + pp - 1):
            if t < M:
                injected = embed(tokens[t])
                x_in = jnp.where(stage == 0, injected, carry)
            else:
                x_in = carry
            y = _stage_forward(x_in, layers_local, c, inv_freq, positions)
            out_idx = t - (pp - 1)
            if 0 <= out_idx < M:
                l, n = head_loss(y, targets[out_idx], mask[out_idx])
                is_last = (stage == pp - 1).astype(jnp.float32)
                loss_sum = loss_sum + l * is_last
                count_sum = count_sum + n * is_last
            if pp > 1:
                carry = jax.lax.ppermute(
                    y, "pp", [(i, i + 1) for i in range(pp - 1)])
            else:
                carry = y
        # aggregate over the pipeline (only last stage contributed) and dp
        loss_sum = jax.lax.psum(loss_sum, ("pp", "dp"))
        count_sum = jax.lax.psum(count_sum, ("pp", "dp"))
        return loss_sum / jnp.maximum(count_sum, 1.0)

    # param specs inside shard_map: layers manual over pp, rest replicated.
    # norms are [L, D] -> P('pp', None); weights [L, A, B] -> P('pp', None, None)
    layers_spec = {
        k: (P("pp", None) if k.endswith("norm") else P("pp", None, None))
        for k in ("attn_norm", "wq", "wk", "wv", "wo", "ffn_norm",
                  "w1", "w3", "w2")}
    param_specs: dict[str, Any] = {"embed": P(), "layers": layers_spec,
                                   "final_norm": P()}
    if not c.tie_embeddings:
        param_specs["lm_head"] = P()
    batch_spec = P(None, "dp", None)  # [M, mb over dp, S]

    from .mesh import shard_map_compat
    sharded_loss = shard_map_compat(
        pipe_loss, mesh=mesh,
        in_specs=(param_specs, batch_spec, batch_spec, batch_spec),
        out_specs=P())

    def train_step(state: TrainState, tokens, targets, mask):
        loss, grads = jax.value_and_grad(sharded_loss)(
            state.params, tokens, targets, mask)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params=params, opt_state=opt_state,
                          step=state.step + 1), loss

    batch_sharding = NamedSharding(mesh, batch_spec)
    return jax.jit(
        train_step,
        in_shardings=(None, batch_sharding, batch_sharding, batch_sharding),
        donate_argnums=(0,) if donate else ())
