from .mesh import create_mesh, mesh_axes
from .sharding import llama_param_specs, shard_params, replicate
from .train import TrainState, make_train_step, cross_entropy_loss

__all__ = [
    "create_mesh", "mesh_axes", "llama_param_specs", "shard_params",
    "replicate", "TrainState", "make_train_step", "cross_entropy_loss",
]
