"""Parameter sharding rules (GSPMD partition specs) per model family.

Megatron-style tensor parallel layout for the Llama pytree: column-
parallel up-projections (shard the output feature dim over ``tp``),
row-parallel down-projections (shard the input feature dim), vocab-
sharded embedding/head. The stacked layer axis (leading ``L``) shards
over ``pp`` when the mesh has a pipeline axis — each stage holds a
contiguous slice of layers, which is exactly what the GPipe runner in
``pipeline.py`` consumes. XLA turns these annotations into
all-gather / reduce-scatter on ICI; we never hand-write them.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _axis(mesh_axes: tuple, name: str) -> str | None:
    return name if name in mesh_axes else None


def llama_param_specs(mesh: Mesh) -> dict:
    """PartitionSpec pytree matching llama_init's structure."""
    ax = mesh.axis_names
    tp = _axis(ax, "tp")
    pp = _axis(ax, "pp")
    specs = {
        "embed": P(tp, None),                 # vocab-sharded
        "layers": {
            "attn_norm": P(pp, None),
            "wq": P(pp, None, tp),            # column parallel
            "wk": P(pp, None, tp),
            "wv": P(pp, None, tp),
            "wo": P(pp, tp, None),            # row parallel
            "ffn_norm": P(pp, None),
            "w1": P(pp, None, tp),
            "w3": P(pp, None, tp),
            "w2": P(pp, tp, None),
        },
        "final_norm": P(None),
    }
    specs["lm_head"] = P(None, tp)
    return specs


def moe_param_specs(mesh: Mesh) -> dict:
    """MoE params: experts sharded over ``ep`` (falling back to ``tp``)."""
    ax = mesh.axis_names
    tp = _axis(ax, "tp")
    pp = _axis(ax, "pp")
    ep = _axis(ax, "ep") or tp
    specs = {
        "embed": P(tp, None),
        "layers": {
            "attn_norm": P(pp, None),
            "wq": P(pp, None, tp),
            "wk": P(pp, None, tp),
            "wv": P(pp, None, tp),
            "wo": P(pp, tp, None),
            "ffn_norm": P(pp, None),
            "gate": P(pp, None, None),
            "w1": P(pp, ep, None, None),      # expert-sharded
            "w3": P(pp, ep, None, None),
            "w2": P(pp, ep, None, None),
        },
        "final_norm": P(None),
    }
    specs["lm_head"] = P(None, tp)
    return specs


def _match_specs(params: Any, specs: Any) -> Any:
    """Prune spec tree to the keys present in params (tied embeddings
    drop lm_head), descending into weight-only-quantized ``{'q','s'}``
    leaves: the int8 matrix keeps the matrix spec, and the per-output-
    channel scales inherit it with the collapsed (size-1) reduction
    axis unsharded — so int8 serving shards exactly like bf16."""
    from ..ops.quant import is_quantized
    if is_quantized(params) and not isinstance(specs, dict):
        scale = params["s"]
        s_spec = P(*(None if scale.shape[i] == 1
                     else (specs[i] if i < len(specs) else None)
                     for i in range(scale.ndim)))
        return {"q": specs, "s": s_spec}
    if isinstance(params, dict):
        return {k: _match_specs(v, specs[k]) for k, v in params.items()}
    return specs


def shard_params(params: Any, mesh: Mesh, specs: Any) -> Any:
    """Place a param pytree onto the mesh per the spec tree."""
    specs = _match_specs(params, specs)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs)


def replicate(tree: Any, mesh: Mesh) -> Any:
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)


def batch_spec(mesh: Mesh) -> P:
    """Input batch: sharded over dp (and sequence over sp if present)."""
    ax = mesh.axis_names
    return P(_axis(ax, "dp"), _axis(ax, "sp"))
