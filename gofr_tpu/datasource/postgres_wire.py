"""PostgreSQL network client speaking the v3 frontend/backend wire
protocol, plus a protocol-faithful mini server.

The reference's SQL datasource dials postgres through database/sql +
lib/pq (sql.go:22-35, sql.go:74); this client implements the protocol
itself over a TCP socket: startup, password authentication (cleartext,
MD5, and SCRAM-SHA-256 per RFC 7677), the simple query cycle
('Q' -> RowDescription/DataRow/CommandComplete), and the extended
query cycle (Parse/Bind/Describe/Execute/Sync) for ``$N``-parameterized
statements. The method surface mirrors :class:`~gofr_tpu.datasource.sql.SQL`
(query/query_row/exec/select/begin/health_check) so handlers and
auto-CRUD swap between sqlite and a network postgres by constructor.

:class:`MiniPostgresServer` implements the backend half of the same
wire protocol over an embedded sqlite engine — STARTUP, the same three
auth exchanges (verifying real MD5 digests and SCRAM proofs), both
query cycles — so tests exercise genuine protocol bytes end-to-end
with no postgres installation.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import re
import secrets
import socket
import socketserver
import sqlite3
import struct
import threading
import time
from typing import Any, Iterator

from contextlib import contextmanager

from . import ProviderMixin
from .sql import QueryLog, SQLError

PROTOCOL_V3 = 196608  # 3.0
SSL_REQUEST = 80877103

# type OIDs we speak (text format)
OID_BOOL = 16
OID_BYTEA = 17
OID_INT8 = 20
OID_INT4 = 23
OID_TEXT = 25
OID_FLOAT8 = 701


class PostgresError(SQLError):
    """Server-reported error (ErrorResponse), with sqlstate."""

    def __init__(self, message: str, sqlstate: str = "") -> None:
        super().__init__(message)
        self.sqlstate = sqlstate


# -------------------------------------------------------------- wire enc

def _cstr(s: str) -> bytes:
    return s.encode() + b"\0"


def _msg(kind: bytes, payload: bytes) -> bytes:
    return kind + struct.pack("!I", len(payload) + 4) + payload


class _Reader:
    """Exact-read wrapper over a blocking socket."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._buf = b""

    def exactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise PostgresError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def message(self) -> tuple[bytes, bytes]:
        kind = self.exactly(1)
        (length,) = struct.unpack("!I", self.exactly(4))
        return kind, self.exactly(length - 4)


def _parse_error(payload: bytes) -> PostgresError:
    fields: dict[bytes, str] = {}
    for part in payload.split(b"\0"):
        if part:
            fields[part[:1]] = part[1:].decode("utf-8", "replace")
    return PostgresError(fields.get(b"M", "unknown error"),
                         sqlstate=fields.get(b"C", ""))


# ------------------------------------------------------------- SCRAM

def _scram_salted_password(password: str, salt: bytes, iters: int) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, iters)


def _hmac256(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _scram_keys(salted: bytes) -> tuple[bytes, bytes, bytes]:
    """-> (client_key, stored_key, server_key)."""
    client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
    stored_key = hashlib.sha256(client_key).digest()
    server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
    return client_key, stored_key, server_key


# -------------------------------------------------------------- row type

class PGRow(dict):
    """A result row: mapping access plus ``keys()`` — the subset of
    ``sqlite3.Row``'s surface the framework relies on (scan_rows,
    auto-CRUD, ORM-lite select)."""

    __slots__ = ()


# ---------------------------------------------------------------- client

class PostgresWire(ProviderMixin):
    """v3-protocol postgres client behind the SQL datasource surface."""

    dialect = "postgres"

    def __init__(self, *, host: str = "localhost", port: int = 5432,
                 user: str = "postgres", password: str = "",
                 database: str = "postgres",
                 timeout_s: float = 30.0) -> None:
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.database = database
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._reader: _Reader | None = None
        self._lock = threading.RLock()
        self.server_params: dict[str, str] = {}

    # ------------------------------------------------------------ startup
    def connect(self) -> None:
        if self._sock is not None:  # reconnect: drop the old socket
            self.close()
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._reader = _Reader(sock)
        try:
            params = b"".join([_cstr("user"), _cstr(self.user),
                               _cstr("database"),
                               _cstr(self.database)]) + b"\0"
            payload = struct.pack("!I", PROTOCOL_V3) + params
            sock.sendall(struct.pack("!I", len(payload) + 4) + payload)
            self._authenticate()
            # drain ParameterStatus/BackendKeyData to ReadyForQuery
            while True:
                kind, body = self._reader.message()
                if kind == b"S":
                    key, _, val = body.rstrip(b"\0").partition(b"\0")
                    self.server_params[key.decode()] = val.decode()
                elif kind == b"Z":
                    break
                elif kind == b"E":
                    raise _parse_error(body)
        except BaseException:
            # don't leak the fd when the handshake/auth fails — the
            # container's log-and-retry connect loop would otherwise
            # leak one socket per attempt
            sock.close()
            self._sock = None
            self._reader = None
            raise
        if self.logger is not None:
            self.logger.info("connected to postgres",
                             host=self.host, port=self.port,
                             database=self.database)

    def _authenticate(self) -> None:
        assert self._sock is not None and self._reader is not None
        while True:
            kind, body = self._reader.message()
            if kind == b"E":
                raise _parse_error(body)
            if kind != b"R":
                raise PostgresError(f"unexpected auth message {kind!r}")
            (code,) = struct.unpack("!I", body[:4])
            if code == 0:  # AuthenticationOk
                return
            if code == 3:  # cleartext
                self._sock.sendall(_msg(b"p", _cstr(self.password)))
            elif code == 5:  # MD5: md5(md5(password+user)+salt)
                salt = body[4:8]
                inner = hashlib.md5(
                    (self.password + self.user).encode()).hexdigest()
                digest = hashlib.md5(
                    inner.encode() + salt).hexdigest()
                self._sock.sendall(_msg(b"p", _cstr("md5" + digest)))
            elif code == 10:  # SASL: pick SCRAM-SHA-256
                mechs = [m for m in body[4:].split(b"\0") if m]
                if b"SCRAM-SHA-256" not in mechs:
                    raise PostgresError(
                        f"server offers no supported SASL mechanism: {mechs}")
                self._scram()
            else:
                raise PostgresError(f"unsupported auth method {code}")

    def _scram(self) -> None:
        assert self._sock is not None and self._reader is not None
        cnonce = base64.b64encode(secrets.token_bytes(18)).decode()
        first_bare = f"n={self.user},r={cnonce}"
        client_first = "n,," + first_bare
        init = (_cstr("SCRAM-SHA-256")
                + struct.pack("!I", len(client_first))
                + client_first.encode())
        self._sock.sendall(_msg(b"p", init))

        kind, body = self._reader.message()
        if kind == b"E":
            raise _parse_error(body)
        (code,) = struct.unpack("!I", body[:4])
        if code != 11:
            raise PostgresError("expected SASLContinue")
        server_first = body[4:].decode()
        attrs = dict(kv.split("=", 1) for kv in server_first.split(","))
        nonce, salt = attrs["r"], base64.b64decode(attrs["s"])
        iters = int(attrs["i"])
        if not nonce.startswith(cnonce):
            raise PostgresError("server nonce does not extend client nonce")

        salted = _scram_salted_password(self.password, salt, iters)
        client_key, stored_key, server_key = _scram_keys(salted)
        final_wo_proof = f"c=biws,r={nonce}"
        auth_msg = f"{first_bare},{server_first},{final_wo_proof}"
        signature = _hmac256(stored_key, auth_msg)
        proof = bytes(a ^ b for a, b in zip(client_key, signature))
        final = f"{final_wo_proof},p={base64.b64encode(proof).decode()}"
        self._sock.sendall(_msg(b"p", final.encode()))

        kind, body = self._reader.message()
        if kind == b"E":
            raise _parse_error(body)
        (code,) = struct.unpack("!I", body[:4])
        if code != 12:
            raise PostgresError("expected SASLFinal")
        verifier = dict(kv.split("=", 1)
                        for kv in body[4:].decode().split(","))
        expect = base64.b64encode(_hmac256(server_key, auth_msg)).decode()
        if not hmac.compare_digest(verifier.get("v", ""), expect):
            raise PostgresError("server SCRAM signature invalid "
                                "(possible man-in-the-middle)")

    # ----------------------------------------------------- instrumented
    def _observe(self, query: str, args: tuple, start: float) -> None:
        duration_us = int((time.perf_counter() - start) * 1e6)
        if self.logger is not None:
            self.logger.debug(
                QueryLog(query, duration_us, args).pretty_print())
        if self.metrics is not None:
            word = query.split(None, 1)[0].lower() if query.split() else "?"
            self.metrics.record_histogram("app_sql_stats",
                                          duration_us / 1e6, type=word)

    def ph(self, n: int) -> str:
        return f"${n}"

    def _require(self) -> tuple[socket.socket, _Reader]:
        if self._sock is None or self._reader is None:
            raise PostgresError("not connected; call connect() first")
        return self._sock, self._reader

    # ------------------------------------------------------- query cycles
    def _simple_query(self, query: str) -> tuple[list[PGRow], str]:
        sock, reader = self._require()
        sock.sendall(_msg(b"Q", _cstr(query)))
        return self._collect(reader)

    def _extended_query(self, query: str,
                        args: tuple) -> tuple[list[PGRow], str]:
        sock, reader = self._require()
        out = _msg(b"P", _cstr("") + _cstr(query) + struct.pack("!H", 0))
        bind = [_cstr(""), _cstr(""),
                # one format code applying to every param: 0 = text
                struct.pack("!H", 1), struct.pack("!h", 0),
                struct.pack("!H", len(args))]
        for a in args:
            if a is None:
                bind.append(struct.pack("!i", -1))
            else:
                if isinstance(a, bytes):  # postgres hex form, still text
                    data = b"\\x" + a.hex().encode()
                else:
                    data = _encode_text_param(a).encode()
                bind.append(struct.pack("!i", len(data)) + data)
        bind.append(struct.pack("!H", 0))  # result formats: default text
        out += _msg(b"B", b"".join(bind))
        out += _msg(b"D", b"P" + _cstr(""))
        out += _msg(b"E", _cstr("") + struct.pack("!I", 0))
        out += _msg(b"S", b"")
        sock.sendall(out)
        return self._collect(reader)

    def _collect(self, reader: _Reader) -> tuple[list[PGRow], str]:
        """Consume one cycle's responses up to ReadyForQuery."""
        columns: list[tuple[str, int]] = []
        rows: list[PGRow] = []
        tag = ""
        error: PostgresError | None = None
        while True:
            kind, body = reader.message()
            if kind == b"T":
                columns = _parse_row_description(body)
            elif kind == b"D":
                rows.append(_parse_data_row(body, columns))
            elif kind == b"C":
                tag = body.rstrip(b"\0").decode()
            elif kind == b"E":
                error = _parse_error(body)
            elif kind == b"Z":
                if error is not None:
                    raise error
                return rows, tag
            # '1' ParseComplete, '2' BindComplete, 'n' NoData,
            # 'S' ParameterStatus, 'N' NoticeResponse: skip

    # --------------------------------------------------- public surface
    def _cycle(self, query: str, args: tuple) -> tuple[list[PGRow], str]:
        """One query cycle; a mid-cycle I/O failure poisons the stream
        (unconsumed response bytes would pair with the NEXT request),
        so the connection is torn down rather than kept."""
        try:
            return (self._extended_query(query, args) if args
                    else self._simple_query(query))
        except (OSError, TimeoutError) as exc:
            self.close()
            raise PostgresError(
                f"connection lost mid-query ({exc}); reconnect required"
            ) from exc

    def query(self, query: str, *args: Any) -> list[PGRow]:
        start = time.perf_counter()
        span = (self.tracer.start_span(f"sql {query.split(None, 1)[0]}")
                if self.tracer is not None else None)
        try:
            with self._lock:
                rows, _ = self._cycle(query, args)
                return rows
        finally:
            if span is not None:
                span.end()
            self._observe(query, args, start)

    def query_row(self, query: str, *args: Any) -> PGRow | None:
        rows = self.query(query, *args)
        return rows[0] if rows else None

    def exec(self, query: str, *args: Any) -> "PGResult":
        start = time.perf_counter()
        span = (self.tracer.start_span(f"sql {query.split(None, 1)[0]}")
                if self.tracer is not None else None)
        try:
            with self._lock:
                _, tag = self._cycle(query, args)
                return PGResult(tag)
        finally:
            if span is not None:
                span.end()
            self._observe(query, args, start)

    @contextmanager
    def begin(self) -> Iterator["PostgresWire"]:
        """BEGIN/COMMIT with rollback-on-raise, mirroring SQL.begin."""
        with self._lock:
            self._cycle("BEGIN", ())
            try:
                yield self
                self._cycle("COMMIT", ())
            except BaseException:
                if self._sock is not None:  # skip if the link just died
                    self._cycle("ROLLBACK", ())
                raise

    def select(self, entity_type: type, query: str, *args: Any) -> list[Any]:
        from dataclasses import fields, is_dataclass
        if not is_dataclass(entity_type):
            raise SQLError("select requires a dataclass type")
        names = [f.name for f in fields(entity_type)]
        return [entity_type(**{n: row[n] for n in names if n in row})
                for row in self.query(query, *args)]

    def health_check(self) -> dict[str, Any]:
        try:
            self.query("SELECT 1")
            return {"status": "UP",
                    "details": {"host": self.host, "port": self.port,
                                "database": self.database}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.sendall(_msg(b"X", b""))
            except OSError:
                pass
            self._sock.close()
            self._sock = None
            self._reader = None


class PGResult:
    """Command outcome: rowcount parsed from the CommandComplete tag
    ("INSERT 0 3" / "UPDATE 2" / "DELETE 1" / "SELECT 4")."""

    def __init__(self, tag: str) -> None:
        self.tag = tag
        parts = tag.split()
        self.rowcount = int(parts[-1]) if parts and parts[-1].isdigit() else 0


def _encode_text_param(value: Any) -> str:
    if isinstance(value, bool):
        return "t" if value else "f"
    return str(value)


def _parse_row_description(body: bytes) -> list[tuple[str, int]]:
    (nfields,) = struct.unpack("!H", body[:2])
    out = []
    off = 2
    for _ in range(nfields):
        end = body.index(b"\0", off)
        name = body[off:end].decode()
        off = end + 1
        _table, _attn, oid, _typlen, _typmod, _fmt = struct.unpack(
            "!IhIhih", body[off:off + 18])
        off += 18
        out.append((name, oid))
    return out


def _decode_text_value(data: bytes, oid: int) -> Any:
    text = data.decode()
    try:
        if oid in (OID_INT8, OID_INT4):
            return int(text)
        if oid == OID_FLOAT8:
            return float(text)
    except ValueError:
        # a mixed-type sqlite column behind the mini server; real
        # postgres can't produce this, degrade to the text
        return text
    if oid == OID_BOOL:
        return text == "t"
    if oid == OID_BYTEA:
        return bytes.fromhex(text[2:]) if text.startswith("\\x") else data
    return text


def _parse_data_row(body: bytes,
                    columns: list[tuple[str, int]]) -> PGRow:
    (nfields,) = struct.unpack("!H", body[:2])
    row = PGRow()
    off = 2
    for i in range(nfields):
        (length,) = struct.unpack("!i", body[off:off + 4])
        off += 4
        name, oid = columns[i] if i < len(columns) else (f"col{i}", OID_TEXT)
        if length == -1:
            row[name] = None
        else:
            row[name] = _decode_text_value(body[off:off + length], oid)
            off += length
    return row


# ------------------------------------------------------------ mini server

def _oid_for(value: Any) -> int:
    if isinstance(value, bool):
        return OID_BOOL
    if isinstance(value, int):
        return OID_INT8
    if isinstance(value, float):
        return OID_FLOAT8
    if isinstance(value, bytes):
        return OID_BYTEA
    return OID_TEXT


def _render_value(value: Any) -> bytes:
    if isinstance(value, bool):
        return b"t" if value else b"f"
    if isinstance(value, bytes):
        return b"\\x" + value.hex().encode()
    return str(value).encode()


# matches a quoted SQL literal (with '' escapes) OR a $N placeholder —
# literals win, so "$15" inside a string stays text
_DOLLAR_RE = re.compile(r"'(?:[^']|'')*'|\$(\d+)")


def _dollar_to_qmark(query: str) -> tuple[str, list[int]]:
    """``$N`` -> ``?`` with the 1-based order of appearance, leaving
    dollar-digit sequences inside string literals untouched."""
    order: list[int] = []

    def sub(match) -> str:
        if match.group(1) is None:  # a quoted literal, not a param
            return match.group(0)
        order.append(int(match.group(1)))
        return "?"

    return _DOLLAR_RE.sub(sub, query), order


class _PGHandler(socketserver.BaseRequestHandler):
    @property
    def mini(self) -> "MiniPostgresServer":
        return self.server.mini  # type: ignore[attr-defined]

    def handle(self) -> None:  # noqa: C901 — one protocol loop
        sock = self.request
        reader = _Reader(sock)
        self.conn = self.mini.new_conn()
        self.state = _ConnState()
        try:
            if not self._startup(sock, reader):
                return
            self._ready(sock)
            statements: dict[str, str] = {}
            portals: dict[str, tuple[str, list[Any]]] = {}
            failed = False  # extended-cycle error: skip until Sync
            while True:
                kind, body = reader.message()
                if kind == b"X":
                    return
                if kind == b"Q":
                    self._simple(sock, body.rstrip(b"\0").decode())
                elif kind == b"S":
                    failed = False
                    self._ready(sock)
                elif failed:
                    continue
                elif kind == b"P":
                    name, _, rest = body.partition(b"\0")
                    query = rest.split(b"\0", 1)[0].decode()
                    statements[name.decode()] = query
                    sock.sendall(_msg(b"1", b""))
                elif kind == b"B":
                    failed = not self._bind(sock, body, statements, portals)
                elif kind == b"D":
                    pass  # RowDescription is sent with Execute's rows
                elif kind == b"E":
                    portal = body.split(b"\0", 1)[0].decode()
                    failed = not self._execute(sock, portals.get(portal))
        except (PostgresError, ConnectionError, OSError):
            return
        finally:
            # a client that vanished mid-transaction must not hold the
            # server-wide tx lock or leave the tx open
            if self.state.in_tx:
                try:
                    self.conn.execute("ROLLBACK")
                except sqlite3.Error:
                    pass
                self.mini.release_tx(self.state)
            self.conn.close()

    # ------------------------------------------------------------ startup
    def _startup(self, sock, reader: _Reader) -> bool:
        (length,) = struct.unpack("!I", reader.exactly(4))
        body = reader.exactly(length - 4)
        (code,) = struct.unpack("!I", body[:4])
        if code == SSL_REQUEST:
            sock.sendall(b"N")  # no TLS on the mini server
            return self._startup(sock, reader)
        if code != PROTOCOL_V3:
            return False
        fields = body[4:].split(b"\0")
        params = {fields[i].decode(): fields[i + 1].decode()
                  for i in range(0, len(fields) - 1, 2) if fields[i]}
        if params.get("user") != self.mini.user:
            self._error(sock, "28000", "role does not exist")
            return False
        if not self._auth(sock, reader):
            self._error(sock, "28P01", "password authentication failed")
            return False
        sock.sendall(_msg(b"R", struct.pack("!I", 0)))
        for key, val in (("server_version", "16.0-mini"),
                         ("client_encoding", "UTF8")):
            sock.sendall(_msg(b"S", _cstr(key) + _cstr(val)))
        sock.sendall(_msg(b"K", struct.pack("!II", os.getpid() & 0xffff,
                                            0x5eed)))
        return True

    def _auth(self, sock, reader: _Reader) -> bool:
        mode = self.mini.auth
        password = self.mini.password
        if mode == "trust":
            return True
        if mode == "password":
            sock.sendall(_msg(b"R", struct.pack("!I", 3)))
            kind, body = reader.message()
            return (kind == b"p"
                    and body.rstrip(b"\0").decode() == password)
        if mode == "md5":
            salt = secrets.token_bytes(4)
            sock.sendall(_msg(b"R", struct.pack("!I", 5) + salt))
            kind, body = reader.message()
            if kind != b"p":
                return False
            inner = hashlib.md5(
                (password + self.mini.user).encode()).hexdigest()
            expect = "md5" + hashlib.md5(inner.encode() + salt).hexdigest()
            return hmac.compare_digest(body.rstrip(b"\0").decode(), expect)
        if mode == "scram-sha-256":
            return self._auth_scram(sock, reader)
        return False

    def _auth_scram(self, sock, reader: _Reader) -> bool:
        sock.sendall(_msg(b"R", struct.pack("!I", 10)
                          + _cstr("SCRAM-SHA-256") + b"\0"))
        kind, body = reader.message()
        if kind != b"p":
            return False
        mech, _, rest = body.partition(b"\0")
        if mech != b"SCRAM-SHA-256":
            return False
        (rlen,) = struct.unpack("!I", rest[:4])
        client_first = rest[4:4 + rlen].decode()
        first_bare = client_first.split(",", 2)[2]
        cattrs = dict(kv.split("=", 1) for kv in first_bare.split(","))
        cnonce = cattrs["r"]

        salt = secrets.token_bytes(16)
        iters = 4096
        snonce = cnonce + base64.b64encode(secrets.token_bytes(12)).decode()
        server_first = (f"r={snonce},s={base64.b64encode(salt).decode()},"
                        f"i={iters}")
        sock.sendall(_msg(b"R", struct.pack("!I", 11)
                          + server_first.encode()))

        kind, body = reader.message()
        if kind != b"p":
            return False
        client_final = body.decode()
        fattrs = dict(kv.split("=", 1) for kv in client_final.split(","))
        if fattrs.get("r") != snonce:
            return False
        proof = base64.b64decode(fattrs["p"])
        final_wo_proof = client_final.rsplit(",p=", 1)[0]
        auth_msg = f"{first_bare},{server_first},{final_wo_proof}"

        salted = _scram_salted_password(self.mini.password, salt, iters)
        client_key, stored_key, server_key = _scram_keys(salted)
        signature = _hmac256(stored_key, auth_msg)
        expect_proof = bytes(a ^ b for a, b in zip(client_key, signature))
        if not hmac.compare_digest(proof, expect_proof):
            return False
        verifier = base64.b64encode(
            _hmac256(server_key, auth_msg)).decode()
        sock.sendall(_msg(b"R", struct.pack("!I", 12)
                          + f"v={verifier}".encode()))
        return True

    # ------------------------------------------------------------- cycles
    def _ready(self, sock, status: bytes = b"I") -> None:
        sock.sendall(_msg(b"Z", status))

    def _error(self, sock, sqlstate: str, message: str) -> None:
        payload = (b"S" + _cstr("ERROR") + b"C" + _cstr(sqlstate)
                   + b"M" + _cstr(message) + b"\0")
        sock.sendall(_msg(b"E", payload))

    def _simple(self, sock, query: str) -> None:
        try:
            rows, columns, tag = self.mini.run_sql(
                self.conn, self.state, query, [])
        except sqlite3.Error as exc:
            self._error(sock, "42601", str(exc))
            self._ready(sock)
            return
        self._send_rows(sock, rows, columns, tag)
        self._ready(sock)

    def _bind(self, sock, body: bytes, statements: dict[str, str],
              portals: dict[str, tuple[str, list[Any]]]) -> bool:
        off = body.index(b"\0")
        portal = body[:off].decode()
        off += 1
        end = body.index(b"\0", off)
        stmt = body[off:end].decode()
        off = end + 1
        (nfmt,) = struct.unpack("!H", body[off:off + 2])
        off += 2
        fmts = struct.unpack(f"!{nfmt}h", body[off:off + 2 * nfmt])
        off += 2 * nfmt
        (nparams,) = struct.unpack("!H", body[off:off + 2])
        off += 2
        params: list[Any] = []
        for i in range(nparams):
            (length,) = struct.unpack("!i", body[off:off + 4])
            off += 4
            if length == -1:
                params.append(None)
                continue
            data = body[off:off + length]
            off += length
            fmt = fmts[i] if i < nfmt else (fmts[0] if nfmt else 0)
            params.append(data if fmt == 1 else _sql_coerce(data.decode()))
        if stmt not in statements:
            self._error(sock, "26000", f"unknown statement {stmt!r}")
            return False
        portals[portal] = (statements[stmt], params)
        sock.sendall(_msg(b"2", b""))
        return True

    def _execute(self, sock,
                 bound: tuple[str, list[Any]] | None) -> bool:
        if bound is None:
            self._error(sock, "34000", "unknown portal")
            return False
        query, params = bound
        try:
            rows, columns, tag = self.mini.run_sql(
                self.conn, self.state, query, params)
        except sqlite3.Error as exc:
            self._error(sock, "42601", str(exc))
            return False
        self._send_rows(sock, rows, columns, tag)
        return True

    def _send_rows(self, sock, rows: list[tuple],
                   columns: list[str], tag: str) -> None:
        if columns:
            desc = [struct.pack("!H", len(columns))]
            for i, name in enumerate(columns):
                # first non-null value decides the OID — a NULL in row
                # 0 must not turn a numeric column into text
                sample = next((row[i] for row in rows
                               if row[i] is not None), None)
                oid = _oid_for(sample) if sample is not None else OID_TEXT
                desc.append(_cstr(name)
                            + struct.pack("!IhIhih", 0, 0, oid, -1, -1, 0))
            sock.sendall(_msg(b"T", b"".join(desc)))
            for row in rows:
                parts = [struct.pack("!H", len(row))]
                for val in row:
                    if val is None:
                        parts.append(struct.pack("!i", -1))
                    else:
                        data = _render_value(val)
                        parts.append(struct.pack("!i", len(data)) + data)
                sock.sendall(_msg(b"D", b"".join(parts)))
        sock.sendall(_msg(b"C", _cstr(tag)))


def _sql_coerce(text: str) -> Any:
    """Text-format parameter -> a Python value sqlite compares sanely.

    Real postgres casts by the statement's inferred parameter types;
    the mini server approximates with value-shape detection.
    """
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    if text in ("t", "true", "f", "false"):
        return text in ("t", "true")
    if text.startswith("\\x"):
        try:
            return bytes.fromhex(text[2:])
        except ValueError:
            pass
    return text


class _ConnState:
    """Per-client-connection transaction state."""

    __slots__ = ("in_tx",)

    def __init__(self) -> None:
        self.in_tx = False


class MiniPostgresServer:
    """Backend half of the v3 protocol over an embedded sqlite engine.

    ``auth`` selects the exchange the server demands: ``trust``,
    ``password``, ``md5``, or ``scram-sha-256`` — each verified for
    real, so a wrong secret fails exactly like production postgres.

    Each client connection gets its own sqlite connection onto one
    shared-cache in-memory database, and an open wire-level BEGIN holds
    a server-wide transaction lock until COMMIT/ROLLBACK — so one
    client's transaction neither sees nor swallows another client's
    statements, matching postgres's per-connection transactions.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 user: str = "postgres", password: str = "secret",
                 auth: str = "md5") -> None:
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.auth = auth
        self._db_uri = (f"file:minipg_{os.getpid()}_{id(self):x}"
                        "?mode=memory&cache=shared")
        # the anchor connection keeps the shared in-memory DB alive
        self._anchor = self.new_conn()
        self._tx_lock = threading.RLock()
        self._server: socketserver.ThreadingTCPServer | None = None
        self._thread: threading.Thread | None = None

    def new_conn(self) -> sqlite3.Connection:
        # true autocommit: the wire-level BEGIN/COMMIT/ROLLBACK coming
        # from clients manage transactions explicitly, like postgres
        return sqlite3.connect(self._db_uri, uri=True,
                               check_same_thread=False,
                               isolation_level=None)

    def release_tx(self, state: _ConnState) -> None:
        if state.in_tx:
            state.in_tx = False
            self._tx_lock.release()

    def run_sql(self, conn: sqlite3.Connection, state: _ConnState,
                query: str,
                params: list[Any]) -> tuple[list[tuple], list[str], str]:
        qmark, order = _dollar_to_qmark(query)
        bad = [i for i in order if not 1 <= i <= len(params)]
        if bad:
            # surfaces as an ErrorResponse, not a torn connection
            raise sqlite3.OperationalError(
                f"there is no parameter ${bad[0]}")
        args = [params[i - 1] for i in order] if order else params
        word = query.split(None, 1)[0].upper() if query.split() else ""
        if word == "BEGIN" and not state.in_tx:
            self._tx_lock.acquire()
            state.in_tx = True
            try:
                conn.execute(qmark, args)
            except BaseException:
                self.release_tx(state)
                raise
            return [], [], "BEGIN"
        if word in ("COMMIT", "ROLLBACK", "END") and state.in_tx:
            try:
                cur = conn.execute(qmark, args)
                cur.fetchall()
            finally:
                self.release_tx(state)
            return [], [], "COMMIT" if word == "END" else word
        if state.in_tx:  # this connection already holds the lock
            cur = conn.execute(qmark, args)
            rows = [tuple(r) for r in cur.fetchall()]
        else:
            with self._tx_lock:
                cur = conn.execute(qmark, args)
                rows = [tuple(r) for r in cur.fetchall()]
        columns = ([d[0] for d in cur.description]
                   if cur.description else [])
        if word == "SELECT" or columns:
            tag = f"SELECT {len(rows)}"
        elif word == "INSERT":
            tag = f"INSERT 0 {cur.rowcount if cur.rowcount > 0 else 0}"
        elif word in ("UPDATE", "DELETE"):
            tag = f"{word} {cur.rowcount if cur.rowcount > 0 else 0}"
        else:
            tag = word or "OK"
        return rows, columns, tag

    def start(self) -> None:
        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = TCP((self.host, self.port), _PGHandler)
        self._server.mini = self  # the handler reads this back
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="mini-postgres")
        self._thread.start()

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self._anchor.close()
