"""Redis-shaped datasource with an in-process backend.

The analog of reference ``datasource/redis`` (redis.go:43, hook.go:17):
a Redis-command surface whose every operation is logged + timed into
``app_redis_stats``. Because this image ships no redis driver, the
default backend is an in-process store with real expiry semantics —
the "miniredis" role SURVEY §4 assigns for hermetic tests — behind the
same interface a real driver would implement, so swapping in a network
client is a constructor change, not an API change.

Commands cover the surface the reference's handler docs exercise:
get/set/setex/del/exists/expire/ttl/incr/decr/hset/hget/hgetall/hdel/
lpush/rpush/lrange/llen/lpop/rpop/sadd/srem/smembers/sismember/keys/
flushdb/ping.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Any

from . import ProviderMixin


class RedisError(Exception):
    pass


class _Entry:
    __slots__ = ("value", "expires_at")

    def __init__(self, value: Any, expires_at: float | None = None) -> None:
        self.value = value
        self.expires_at = expires_at

    def expired(self, now: float) -> bool:
        return self.expires_at is not None and now >= self.expires_at


class Redis(ProviderMixin):
    """In-process Redis-command store with observability hooks."""

    def __init__(self, *, host: str = "localhost", port: int = 6379) -> None:
        self.host, self.port = host, port
        self._data: dict[str, _Entry] = {}
        self._lock = threading.RLock()
        self._connected = False

    def connect(self) -> None:
        self._connected = True
        if self.logger is not None:
            self.logger.info("connected to Redis",
                             addr=f"{self.host}:{self.port}")

    # ------------------------------------------------- instrumented core
    def _observed(self, command: str, fn, *args):
        """Run one command under the logging/metrics hook
        (reference redis/hook.go:17)."""
        if not self._connected:
            raise RedisError("redis not connected; call connect() first")
        start = time.perf_counter()
        try:
            with self._lock:
                # per-key lazy expiry happens in _live(); a full sweep
                # here would make every O(1) op O(total keys)
                return fn(*args)
        finally:
            micros = int((time.perf_counter() - start) * 1e6)
            if self.logger is not None:
                self.logger.debug(f"REDIS {micros:6d}µs {command}")
            if self.metrics is not None:
                self.metrics.record_histogram("app_redis_stats", micros / 1e6,
                                              type=command.split()[0].lower())

    def _sweep(self) -> None:
        now = time.monotonic()
        dead = [k for k, e in self._data.items() if e.expired(now)]
        for k in dead:
            del self._data[k]

    def _live(self, key: str) -> _Entry | None:
        e = self._data.get(key)
        if e is None or e.expired(time.monotonic()):
            self._data.pop(key, None)
            return None
        return e

    # ------------------------------------------------------------ string
    def set(self, key: str, value: Any, ex: float | None = None) -> bool:
        if ex is not None and ex <= 0:
            # real redis rejects SET ... EX 0 rather than storing forever
            raise RedisError("invalid expire time in 'set' command")

        def op():
            expires = time.monotonic() + ex if ex is not None else None
            self._data[key] = _Entry(value, expires)
            return True
        return self._observed(f"SET {key}", op)

    def setex(self, key: str, seconds: float, value: Any) -> bool:
        return self.set(key, value, ex=seconds)

    def get(self, key: str) -> Any:
        def op():
            e = self._live(key)
            return None if e is None else e.value
        return self._observed(f"GET {key}", op)

    def delete(self, *keys: str) -> int:
        def op():
            n = 0
            for k in keys:
                if self._live(k) is not None:
                    del self._data[k]
                    n += 1
            return n
        return self._observed(f"DEL {' '.join(keys)}", op)

    def exists(self, *keys: str) -> int:
        def op():
            return sum(1 for k in keys if self._live(k) is not None)
        return self._observed(f"EXISTS {' '.join(keys)}", op)

    def expire(self, key: str, seconds: float) -> bool:
        def op():
            e = self._live(key)
            if e is None:
                return False
            e.expires_at = time.monotonic() + seconds
            return True
        return self._observed(f"EXPIRE {key}", op)

    def ttl(self, key: str) -> float:
        """-2 missing, -1 no expiry (redis semantics)."""
        def op():
            e = self._live(key)
            if e is None:
                return -2
            if e.expires_at is None:
                return -1
            return max(0.0, e.expires_at - time.monotonic())
        return self._observed(f"TTL {key}", op)

    def _incr_by(self, key: str, delta: int) -> int:
        e = self._live(key)
        current = 0 if e is None else int(e.value)
        current += delta
        if e is None:
            self._data[key] = _Entry(current)
        else:
            e.value = current
        return current

    def incr(self, key: str, by: int = 1) -> int:
        return self._observed(f"INCR {key}", self._incr_by, key, by)

    def decr(self, key: str, by: int = 1) -> int:
        return self._observed(f"DECR {key}", self._incr_by, key, -by)

    # -------------------------------------------------------------- hash
    def _hash(self, key: str, create: bool = False) -> dict | None:
        e = self._live(key)
        if e is None:
            if not create:
                return None
            e = _Entry({})
            self._data[key] = e
        if not isinstance(e.value, dict):
            raise RedisError("WRONGTYPE not a hash")
        return e.value

    def hset(self, key: str, field: str, value: Any) -> int:
        def op():
            h = self._hash(key, create=True)
            fresh = field not in h
            h[field] = value
            return int(fresh)
        return self._observed(f"HSET {key} {field}", op)

    def hget(self, key: str, field: str) -> Any:
        def op():
            h = self._hash(key)
            return None if h is None else h.get(field)
        return self._observed(f"HGET {key} {field}", op)

    def hgetall(self, key: str) -> dict:
        def op():
            h = self._hash(key)
            return {} if h is None else dict(h)
        return self._observed(f"HGETALL {key}", op)

    def hdel(self, key: str, *fs: str) -> int:
        def op():
            h = self._hash(key)
            if h is None:
                return 0
            return sum(1 for f in fs if h.pop(f, None) is not None)
        return self._observed(f"HDEL {key}", op)

    # -------------------------------------------------------------- list
    def _list(self, key: str, create: bool = False) -> list | None:
        e = self._live(key)
        if e is None:
            if not create:
                return None
            e = _Entry([])
            self._data[key] = e
        if not isinstance(e.value, list):
            raise RedisError("WRONGTYPE not a list")
        return e.value

    def lpush(self, key: str, *values: Any) -> int:
        def op():
            lst = self._list(key, create=True)
            for v in values:
                lst.insert(0, v)
            return len(lst)
        return self._observed(f"LPUSH {key}", op)

    def rpush(self, key: str, *values: Any) -> int:
        def op():
            lst = self._list(key, create=True)
            lst.extend(values)
            return len(lst)
        return self._observed(f"RPUSH {key}", op)

    def lrange(self, key: str, start: int, stop: int) -> list:
        def op():
            lst = self._list(key)
            if lst is None:
                return []
            stop_ = len(lst) if stop == -1 else stop + 1
            return lst[start:stop_]
        return self._observed(f"LRANGE {key}", op)

    def llen(self, key: str) -> int:
        def op():
            lst = self._list(key)
            return 0 if lst is None else len(lst)
        return self._observed(f"LLEN {key}", op)

    def lpop(self, key: str) -> Any:
        def op():
            lst = self._list(key)
            return lst.pop(0) if lst else None
        return self._observed(f"LPOP {key}", op)

    def rpop(self, key: str) -> Any:
        def op():
            lst = self._list(key)
            return lst.pop() if lst else None
        return self._observed(f"RPOP {key}", op)

    # --------------------------------------------------------------- set
    def _set(self, key: str, create: bool = False) -> set | None:
        e = self._live(key)
        if e is None:
            if not create:
                return None
            e = _Entry(set())
            self._data[key] = e
        if not isinstance(e.value, set):
            raise RedisError("WRONGTYPE not a set")
        return e.value

    def sadd(self, key: str, *members: Any) -> int:
        def op():
            s = self._set(key, create=True)
            before = len(s)
            s.update(members)
            return len(s) - before
        return self._observed(f"SADD {key}", op)

    def srem(self, key: str, *members: Any) -> int:
        def op():
            s = self._set(key)
            if s is None:
                return 0
            before = len(s)
            s.difference_update(members)
            return before - len(s)
        return self._observed(f"SREM {key}", op)

    def smembers(self, key: str) -> set:
        def op():
            s = self._set(key)
            return set() if s is None else set(s)
        return self._observed(f"SMEMBERS {key}", op)

    def sismember(self, key: str, member: Any) -> bool:
        def op():
            s = self._set(key)
            return s is not None and member in s
        return self._observed(f"SISMEMBER {key}", op)

    # ------------------------------------------------------------- admin
    def keys(self, pattern: str = "*") -> list[str]:
        def op():
            self._sweep()  # keys() reads _data wholesale, so expire first
            return [k for k in self._data if fnmatch.fnmatchcase(k, pattern)]
        return self._observed(f"KEYS {pattern}", op)

    def flushdb(self) -> bool:
        def op():
            self._data.clear()
            return True
        return self._observed("FLUSHDB", op)

    def ping(self) -> bool:
        return self._observed("PING", lambda: True)

    def health_check(self) -> dict[str, Any]:
        try:
            self.ping()
            return {"status": "UP",
                    "details": {"addr": f"{self.host}:{self.port}",
                                "keys": len(self._data)}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        self._connected = False


def new_redis(config: Any, logger: Any = None, metrics: Any = None,
              tracer: Any = None):
    """Env-driven constructor (reference redis/redis.go:43): None when
    REDIS_HOST unset. ``REDIS_MODE=network`` selects the RESP2 wire
    client (:class:`~gofr_tpu.datasource.redis_wire.RedisWire`) — the
    promised constructor swap; the default stays the embedded engine so
    apps run hermetically without a server."""
    host = config.get("REDIS_HOST") if config else None
    if not host:
        return None
    mode = config.get_or_default("REDIS_MODE", "embedded").lower()
    if mode == "network":
        from .redis_wire import RedisWire
        r: Any = RedisWire(host=host,
                           port=int(config.get_or_default("REDIS_PORT",
                                                          "6379")))
    else:
        r = Redis(host=host,
                  port=int(config.get_or_default("REDIS_PORT", "6379")))
    if logger is not None:
        r.use_logger(logger)
    if metrics is not None:
        r.use_metrics(metrics)
    if tracer is not None:
        r.use_tracer(tracer)
    try:
        r.connect()
    except OSError as exc:
        # a briefly-down server must not crash app boot: health reports
        # DOWN and the wire client redials lazily on first use
        if logger is not None:
            logger.error(f"redis connect failed (will retry on use): {exc}")
    return r
