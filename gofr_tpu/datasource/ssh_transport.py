"""SSH2 transport implemented from the RFCs, client and server halves.

The reference's SFTP module rides a Go SSH stack (datasource/file/sftp
over pkg/sftp + x/crypto/ssh); this is the equivalent transport built
from the specification with only the stdlib and the ``cryptography``
primitives already in the image:

- RFC 4253 binary packet protocol: version exchange, KEXINIT
  negotiation, curve25519-sha256 key exchange, ssh-ed25519 host keys,
  aes128-ctr encryption, hmac-sha2-256 integrity, RFC 4253 §7.2 key
  derivation.
- RFC 4252 password authentication (client sends, server verifies).
- RFC 4254 connection protocol: one "session" channel carrying a
  subsystem (SFTP rides on top, :mod:`.sftp_wire`), with window
  accounting.

One algorithm per slot, deliberately: the negotiation lists are real,
but both halves of this framework offer exactly the modern suite
above, which also interoperates with OpenSSH defaults.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import socket
import struct
from typing import Any

VERSION_STRING = "SSH-2.0-gofrssh_0.1"

MSG_DISCONNECT = 1
MSG_SERVICE_REQUEST = 5
MSG_SERVICE_ACCEPT = 6
MSG_KEXINIT = 20
MSG_NEWKEYS = 21
MSG_KEX_ECDH_INIT = 30
MSG_KEX_ECDH_REPLY = 31
MSG_USERAUTH_REQUEST = 50
MSG_USERAUTH_FAILURE = 51
MSG_USERAUTH_SUCCESS = 52
MSG_CHANNEL_OPEN = 90
MSG_CHANNEL_OPEN_CONFIRMATION = 91
MSG_CHANNEL_OPEN_FAILURE = 92
MSG_CHANNEL_WINDOW_ADJUST = 93
MSG_CHANNEL_DATA = 94
MSG_CHANNEL_EOF = 96
MSG_CHANNEL_CLOSE = 97
MSG_CHANNEL_REQUEST = 98
MSG_CHANNEL_SUCCESS = 99
MSG_CHANNEL_FAILURE = 100

KEX_ALG = "curve25519-sha256"
HOSTKEY_ALG = "ssh-ed25519"
CIPHER_ALG = "aes128-ctr"
MAC_ALG = "hmac-sha2-256"

_WINDOW = 1 << 30
_MAX_PACKET = 1 << 15


class SSHError(Exception):
    pass


class SSHAuthError(SSHError):
    pass


# ----------------------------------------------------------- wire atoms

def sb(data: bytes) -> bytes:
    """SSH string."""
    return struct.pack("!I", len(data)) + data


def ss(text: str) -> bytes:
    return sb(text.encode())


def mpint(n: int) -> bytes:
    if n == 0:
        return sb(b"")
    raw = n.to_bytes((n.bit_length() + 8) // 8, "big")  # leading 0 bit
    return sb(raw)


class Reader:
    """Sequential parser over one payload."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.off = 0

    def byte(self) -> int:
        self.off += 1
        return self.data[self.off - 1]

    def boolean(self) -> bool:
        return self.byte() != 0

    def uint32(self) -> int:
        (v,) = struct.unpack_from("!I", self.data, self.off)
        self.off += 4
        return v

    def uint64(self) -> int:
        (v,) = struct.unpack_from("!Q", self.data, self.off)
        self.off += 8
        return v

    def string(self) -> bytes:
        n = self.uint32()
        out = self.data[self.off:self.off + n]
        self.off += n
        return out

    def text(self) -> str:
        return self.string().decode()

    def namelist(self) -> list[str]:
        raw = self.text()
        return raw.split(",") if raw else []


# ------------------------------------------------------------- transport

class _Stream:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self._buf = b""

    def exactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise SSHError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def line(self) -> bytes:
        while b"\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise SSHError("connection closed during version exchange")
            self._buf += chunk
        line, _, self._buf = self._buf.partition(b"\n")
        return line.rstrip(b"\r")


def _kexinit_payload() -> bytes:
    lists = [
        KEX_ALG, HOSTKEY_ALG, CIPHER_ALG, CIPHER_ALG, MAC_ALG, MAC_ALG,
        "none", "none", "", "",
    ]
    out = bytes([MSG_KEXINIT]) + os.urandom(16)
    for names in lists:
        out += ss(names)
    out += b"\x00" + struct.pack("!I", 0)
    return out


def _derive(k: bytes, h: bytes, tag: bytes, session_id: bytes,
            length: int) -> bytes:
    out = hashlib.sha256(k + h + tag + session_id).digest()
    while len(out) < length:
        out += hashlib.sha256(k + h + out).digest()
    return out[:length]


class _Direction:
    """One flow (c→s or s→c): cipher stream + MAC + sequence number."""

    def __init__(self, key: bytes, iv: bytes, mac_key: bytes) -> None:
        from cryptography.hazmat.primitives.ciphers import (
            Cipher, algorithms, modes)
        self._cipher = Cipher(algorithms.AES(key), modes.CTR(iv))
        self.enc = self._cipher.encryptor()
        self.dec = self._cipher.decryptor()
        self.mac_key = mac_key
        self.seq = 0

    def mac(self, packet: bytes) -> bytes:
        data = struct.pack("!I", self.seq) + packet
        return hmac_mod.new(self.mac_key, data, hashlib.sha256).digest()


class SSHTransport:
    """Post-handshake packet transport shared by client and server."""

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.stream = _Stream(sock)
        self.session_id = b""
        self._out: _Direction | None = None
        self._in: _Direction | None = None
        self._out_seq = 0
        self._in_seq = 0
        self._peer_window = _WINDOW  # replaced by the channel reply
        self._pending_data: list[bytes] = []

    # ------------------------------------------------------ raw packets
    def send_packet(self, payload: bytes) -> None:
        block = 16 if self._out else 8
        pad = block - ((5 + len(payload)) % block)
        if pad < 4:
            pad += block
        packet = struct.pack("!IB", 1 + len(payload) + pad, pad) \
            + payload + os.urandom(pad)
        if self._out is None:
            self.sock.sendall(packet)
            self._out_seq += 1
            return
        self._out.seq = self._out_seq
        mac = self._out.mac(packet)
        self.sock.sendall(self._out.enc.update(packet) + mac)
        self._out_seq += 1

    def recv_packet(self) -> bytes:
        if self._in is None:
            head = self.stream.exactly(4)
            (length,) = struct.unpack("!I", head)
            if not 5 <= length <= 35000:  # RFC 4253 §6.1
                raise SSHError(f"packet length {length} out of bounds")
            body = self.stream.exactly(length)
            self._in_seq += 1
            pad = body[0]
            return body[1:length - pad]
        head = self._in.dec.update(self.stream.exactly(16))
        (length,) = struct.unpack("!I", head[:4])
        # bound before allocating: length is wire-supplied and the MAC
        # is only checked after the remainder is read.  RFC 4253 §6.1:
        # minimum total packet is one cipher block (16), i.e. a length
        # field of 12, and receivers must handle up to 35000 total.
        if not 12 <= length <= 35000:
            raise SSHError(f"packet length {length} out of bounds")
        rest = self._in.dec.update(self.stream.exactly(length - 12))
        mac = self.stream.exactly(32)
        packet = head + rest
        self._in.seq = self._in_seq
        if not hmac_mod.compare_digest(self._in.mac(packet), mac):
            raise SSHError("MAC verification failed")
        self._in_seq += 1
        pad = packet[4]
        return packet[5:4 + length - pad]

    # --------------------------------------------------------- handshake
    def _exchange_versions(self, ours: str) -> str:
        self.sock.sendall((ours + "\r\n").encode())
        while True:
            line = self.stream.line()
            if line.startswith(b"SSH-"):
                return line.decode("latin-1")

    def _activate(self, k_mp: bytes, h: bytes, *, client: bool) -> None:
        if not self.session_id:
            self.session_id = h
        sid = self.session_id

        def dk(tag: bytes, length: int) -> bytes:
            return _derive(k_mp, h, tag, sid, length)

        c2s = _Direction(dk(b"C", 16), dk(b"A", 16), dk(b"E", 32))
        s2c = _Direction(dk(b"D", 16), dk(b"B", 16), dk(b"F", 32))
        self._out, self._in = (c2s, s2c) if client else (s2c, c2s)

    def _check_kexinit(self, payload: bytes) -> None:
        r = Reader(payload)
        if r.byte() != MSG_KEXINIT:
            raise SSHError("expected KEXINIT")
        r.off += 16  # cookie
        kex, hostkey = r.namelist(), r.namelist()
        c2s_ciph, s2c_ciph = r.namelist(), r.namelist()
        c2s_mac, s2c_mac = r.namelist(), r.namelist()
        if (KEX_ALG not in kex or HOSTKEY_ALG not in hostkey
                or CIPHER_ALG not in c2s_ciph or CIPHER_ALG not in s2c_ciph
                or MAC_ALG not in c2s_mac or MAC_ALG not in s2c_mac):
            raise SSHError(
                f"no common algorithms (peer kex={kex[:3]}, "
                f"hostkey={hostkey[:3]})")

    # ---------------------------------------------------------- channel
    def _consume(self, payload: bytes) -> bytes | None:
        """Account one incoming packet; -> DATA bytes if it carried
        channel data, else None. Raises on close/disconnect."""
        kind = payload[0]
        if kind == MSG_CHANNEL_DATA:
            r = Reader(payload[1:])
            r.uint32()
            return r.string()
        if kind == MSG_CHANNEL_WINDOW_ADJUST:
            r = Reader(payload[1:])
            r.uint32()
            self._peer_window += r.uint32()
            return None
        if kind in (MSG_CHANNEL_CLOSE, MSG_DISCONNECT):
            raise SSHError("channel closed by peer")
        # globals (e.g. hostkeys-00@openssh.com), debug, ignore, EOF
        return None

    def open_session_channel(self) -> int:
        """Client side: -> recipient (server) channel id."""
        self.send_packet(bytes([MSG_CHANNEL_OPEN]) + ss("session")
                         + struct.pack("!III", 0, _WINDOW, _MAX_PACKET))
        while True:  # sshd may interleave global requests here
            payload = self.recv_packet()
            kind = payload[0]
            if kind == MSG_CHANNEL_OPEN_CONFIRMATION:
                r = Reader(payload[1:])
                r.uint32()  # our id echo
                sender = r.uint32()
                self._peer_window = r.uint32()
                return sender
            if kind == MSG_CHANNEL_OPEN_FAILURE:
                raise SSHError("channel open refused")
            self._consume(payload)

    def request_subsystem(self, channel: int, name: str) -> None:
        self.send_packet(bytes([MSG_CHANNEL_REQUEST])
                         + struct.pack("!I", channel) + ss("subsystem")
                         + b"\x01" + ss(name))
        while True:
            payload = self.recv_packet()
            kind = payload[0]
            if kind == MSG_CHANNEL_SUCCESS:
                return
            if kind == MSG_CHANNEL_FAILURE:
                raise SSHError(f"subsystem {name!r} refused")
            self._consume(payload)

    def send_channel_data(self, channel: int, data: bytes) -> None:
        for i in range(0, len(data), _MAX_PACKET - 1024):
            chunk = data[i:i + _MAX_PACKET - 1024]
            # flow control: wait for WINDOW_ADJUST when the peer's
            # window is exhausted (data that arrives meanwhile queues
            # for recv_channel_data — the protocols above are strictly
            # request/response, so this stays bounded)
            while self._peer_window < len(chunk):
                got = self._consume(self.recv_packet())
                if got is not None:
                    self._pending_data.append(got)
            self._peer_window -= len(chunk)
            self.send_packet(bytes([MSG_CHANNEL_DATA])
                             + struct.pack("!I", channel) + sb(chunk))

    def recv_channel_data(self) -> bytes:
        """Next CHANNEL_DATA payload; window/ignore frames are consumed."""
        if self._pending_data:
            return self._pending_data.pop(0)
        while True:
            got = self._consume(self.recv_packet())
            if got is not None:
                return got


# ---------------------------------------------------------------- client

class SSHClientTransport(SSHTransport):
    def handshake(self, *, username: str, password: str,
                  expected_host_key: bytes | None = None,
                  insecure_skip_host_key: bool = False) -> None:
        """Version exchange → kex → NEWKEYS → password auth.

        Host-key policy mirrors x/crypto/ssh's HostKeyCallback: the
        caller must either pin ``expected_host_key`` or explicitly opt
        in to an unauthenticated connection — the Ed25519 signature
        alone only proves the peer owns *some* key, so a silent default
        would hand the password to any man in the middle."""
        if expected_host_key is None and not insecure_skip_host_key:
            raise SSHError(
                "no host key policy: pass expected_host_key=... or "
                "insecure_skip_host_key=True (MITM-able; test only)")
        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PrivateKey)
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PublicKey)
        from cryptography.hazmat.primitives import serialization

        v_s = self._exchange_versions(VERSION_STRING)
        i_c = _kexinit_payload()
        self.send_packet(i_c)
        i_s = self.recv_packet()
        self._check_kexinit(i_s)

        eph = X25519PrivateKey.generate()
        q_c = eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        self.send_packet(bytes([MSG_KEX_ECDH_INIT]) + sb(q_c))

        r = Reader(self.recv_packet())
        if r.byte() != MSG_KEX_ECDH_REPLY:
            raise SSHError("expected KEX_ECDH_REPLY")
        k_s = r.string()
        q_s = r.string()
        signature_blob = r.string()

        kr = Reader(k_s)
        if kr.text() != HOSTKEY_ALG:
            raise SSHError("unexpected host key type")
        host_pub_raw = kr.string()
        if expected_host_key is not None \
                and host_pub_raw != expected_host_key:
            raise SSHError("host key mismatch (possible MITM)")

        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PublicKey)
        shared = eph.exchange(X25519PublicKey.from_public_bytes(q_s))
        k_int = int.from_bytes(shared, "big")
        h = hashlib.sha256(
            ss(VERSION_STRING) + ss(v_s) + sb(i_c) + sb(i_s)
            + sb(k_s) + sb(q_c) + sb(q_s) + mpint(k_int)).digest()

        sr = Reader(signature_blob)
        if sr.text() != HOSTKEY_ALG:
            raise SSHError("unexpected signature type")
        raw_sig = sr.string()
        try:
            Ed25519PublicKey.from_public_bytes(host_pub_raw).verify(
                raw_sig, h)
        except Exception as exc:
            raise SSHError(f"host signature invalid: {exc}") from exc

        self.send_packet(bytes([MSG_NEWKEYS]))
        if self.recv_packet()[0] != MSG_NEWKEYS:
            raise SSHError("expected NEWKEYS")
        self._activate(mpint(k_int), h, client=True)

        # ------------------------------------------------------- auth
        self.send_packet(bytes([MSG_SERVICE_REQUEST]) + ss("ssh-userauth"))
        if self.recv_packet()[0] != MSG_SERVICE_ACCEPT:
            raise SSHError("userauth service refused")
        self.send_packet(
            bytes([MSG_USERAUTH_REQUEST]) + ss(username)
            + ss("ssh-connection") + ss("password") + b"\x00"
            + ss(password))
        kind = self.recv_packet()[0]
        if kind != MSG_USERAUTH_SUCCESS:
            raise SSHAuthError("password authentication failed")


# ---------------------------------------------------------------- server

class SSHServerTransport(SSHTransport):
    def __init__(self, sock: socket.socket, *, host_key: Any,
                 users: dict[str, str]) -> None:
        super().__init__(sock)
        self.host_key = host_key  # Ed25519PrivateKey
        self.users = users
        self.username = ""

    def handshake(self) -> None:
        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PrivateKey, X25519PublicKey)
        from cryptography.hazmat.primitives import serialization

        v_c = self._exchange_versions(VERSION_STRING)
        i_s = _kexinit_payload()
        self.send_packet(i_s)
        i_c = self.recv_packet()
        self._check_kexinit(i_c)

        r = Reader(self.recv_packet())
        if r.byte() != MSG_KEX_ECDH_INIT:
            raise SSHError("expected KEX_ECDH_INIT")
        q_c = r.string()

        eph = X25519PrivateKey.generate()
        q_s = eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        shared = eph.exchange(X25519PublicKey.from_public_bytes(q_c))
        k_int = int.from_bytes(shared, "big")

        host_pub = self.host_key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        k_s = ss(HOSTKEY_ALG) + sb(host_pub)
        h = hashlib.sha256(
            ss(v_c) + ss(VERSION_STRING) + sb(i_c) + sb(i_s)
            + sb(k_s) + sb(q_c) + sb(q_s) + mpint(k_int)).digest()
        signature = ss(HOSTKEY_ALG) + sb(self.host_key.sign(h))

        self.send_packet(bytes([MSG_KEX_ECDH_REPLY]) + sb(k_s) + sb(q_s)
                         + sb(signature))
        self.send_packet(bytes([MSG_NEWKEYS]))
        if self.recv_packet()[0] != MSG_NEWKEYS:
            raise SSHError("expected NEWKEYS")
        self._activate(mpint(k_int), h, client=False)

        # ------------------------------------------------------- auth
        r = Reader(self.recv_packet())
        if r.byte() != MSG_SERVICE_REQUEST or r.text() != "ssh-userauth":
            raise SSHError("expected ssh-userauth service request")
        self.send_packet(bytes([MSG_SERVICE_ACCEPT]) + ss("ssh-userauth"))

        for _ in range(8):  # a few tries, like sshd MaxAuthTries
            r = Reader(self.recv_packet())
            if r.byte() != MSG_USERAUTH_REQUEST:
                raise SSHError("expected USERAUTH_REQUEST")
            username = r.text()
            r.text()  # service
            method = r.text()
            if method == "password":
                r.boolean()
                password = r.text()
                expected = self.users.get(username)
                if expected is not None and hmac_mod.compare_digest(
                        expected.encode(), password.encode()):
                    self.username = username
                    self.send_packet(bytes([MSG_USERAUTH_SUCCESS]))
                    return
            self.send_packet(bytes([MSG_USERAUTH_FAILURE])
                             + ss("password") + b"\x00")
        raise SSHAuthError("too many auth failures")

    def accept_subsystem(self) -> tuple[int, str]:
        """-> (client channel id, subsystem name) after confirming the
        session channel."""
        r = Reader(self.recv_packet())
        if r.byte() != MSG_CHANNEL_OPEN or r.text() != "session":
            raise SSHError("expected session CHANNEL_OPEN")
        client_channel = r.uint32()
        self.send_packet(bytes([MSG_CHANNEL_OPEN_CONFIRMATION])
                         + struct.pack("!IIII", client_channel, 0,
                                       _WINDOW, _MAX_PACKET))
        r = Reader(self.recv_packet())
        if r.byte() != MSG_CHANNEL_REQUEST:
            raise SSHError("expected CHANNEL_REQUEST")
        r.uint32()
        if r.text() != "subsystem":
            raise SSHError("only subsystem requests supported")
        want_reply = r.boolean()
        name = r.text()
        if want_reply:
            self.send_packet(bytes([MSG_CHANNEL_SUCCESS])
                             + struct.pack("!I", client_channel))
        return client_channel, name
