"""SFTP v3 over the framework's own SSH2 transport, plus a mini
SSH+SFTP server.

The reference's SFTP module is a driver-backed network client
(datasource/file/sftp over pkg/sftp). This is the protocol itself:
SFTP version 3 (draft-ietf-secsh-filexfer-02) request/response packets
— OPEN/READ/WRITE/CLOSE, OPENDIR/READDIR, STAT, REMOVE/RENAME/MKDIR/
RMDIR — framed over an authenticated
:class:`~gofr_tpu.datasource.ssh_transport.SSHClientTransport`
session channel. :class:`SFTPWire` exposes the framework's FileSystem
surface (create/read/append/remove/rename/stat/exists/mkdir/read_dir/
read_rows), and also the paramiko-style verbs
(putfo/getfo/listdir/...) that
:class:`~gofr_tpu.datasource.ftp.SFTPFileSystem` accepts as an
injected client — so the previously injection-only SFTP slot now has
a native stack.

:class:`MiniSFTPServer` is a real SSH server (verified password auth,
ed25519 host key, the same from-spec transport) serving a jailed
directory tree — hermetic tests run the full stack: kex, encryption,
MAC, auth, channels, SFTP.
"""

from __future__ import annotations

import io
import os
import posixpath
import socket
import socketserver
import stat as stat_mod
import struct
import threading
from pathlib import Path
from typing import Any

from . import Instrumented
from .file_store import FileError, FileInfo, RowReader
from .ssh_transport import (Reader, SSHAuthError, SSHClientTransport,
                            SSHError, SSHServerTransport, sb, ss)

FXP_INIT = 1
FXP_VERSION = 2
FXP_OPEN = 3
FXP_CLOSE = 4
FXP_READ = 5
FXP_WRITE = 6
FXP_LSTAT = 7
FXP_OPENDIR = 11
FXP_READDIR = 12
FXP_REMOVE = 13
FXP_MKDIR = 14
FXP_RMDIR = 15
FXP_STAT = 17
FXP_RENAME = 18
FXP_STATUS = 101
FXP_HANDLE = 102
FXP_DATA = 103
FXP_NAME = 104
FXP_ATTRS = 105

FX_OK = 0
FX_EOF = 1
FX_NO_SUCH_FILE = 2
FX_PERMISSION_DENIED = 3
FX_FAILURE = 4

PFLAG_READ = 0x01
PFLAG_WRITE = 0x02
PFLAG_APPEND = 0x04
PFLAG_CREAT = 0x08
PFLAG_TRUNC = 0x10

ATTR_SIZE = 0x01
ATTR_PERMISSIONS = 0x04
ATTR_ACMODTIME = 0x08

_CHUNK = 24 * 1024


class SFTPError(FileError):
    def __init__(self, message: str, code: int = FX_FAILURE) -> None:
        super().__init__(message)
        self.code = code


def _attrs(size: int, is_dir: bool, mtime: float) -> bytes:
    perms = (stat_mod.S_IFDIR | 0o755) if is_dir else (stat_mod.S_IFREG
                                                       | 0o644)
    return struct.pack("!I", ATTR_SIZE | ATTR_PERMISSIONS | ATTR_ACMODTIME) \
        + struct.pack("!Q", size) + struct.pack("!I", perms) \
        + struct.pack("!II", int(mtime), int(mtime))


def _parse_attrs(r: Reader) -> tuple[int, bool, float]:
    """-> (size, is_dir, mtime)."""
    flags = r.uint32()
    size = r.uint64() if flags & ATTR_SIZE else 0
    if flags & 0x02:  # uid/gid
        r.uint32()
        r.uint32()
    perms = r.uint32() if flags & ATTR_PERMISSIONS else 0
    mtime = 0.0
    if flags & ATTR_ACMODTIME:
        r.uint32()
        mtime = float(r.uint32())
    # S_ISDIR, not a bit test: S_IFSOCK contains the S_IFDIR bit
    return size, stat_mod.S_ISDIR(perms), mtime


# ----------------------------------------------------------------- client

class SFTPWire(Instrumented):
    """FileSystem surface over SFTP v3 on the framework's SSH stack."""

    metric = "app_sftp_stats"
    log_tag = "SFTP"

    def __init__(self, host: str = "127.0.0.1", port: int = 22, *,
                 username: str = "", password: str = "",
                 expected_host_key: bytes | None = None,
                 insecure_skip_host_key: bool = False,
                 timeout_s: float = 30.0) -> None:
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.expected_host_key = expected_host_key
        self.insecure_skip_host_key = insecure_skip_host_key
        self.timeout_s = timeout_s
        self._transport: SSHClientTransport | None = None
        self._channel = 0
        self._ids = 0
        self._buf = b""
        self._lock = threading.RLock()

    # ------------------------------------------------------------ session
    def connect(self) -> None:
        if self._transport is not None:
            self.close()
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        transport = SSHClientTransport(sock)
        try:
            transport.handshake(
                username=self.username, password=self.password,
                expected_host_key=self.expected_host_key,
                insecure_skip_host_key=self.insecure_skip_host_key)
            self._channel = transport.open_session_channel()
            transport.request_subsystem(self._channel, "sftp")
            self._transport = transport
            self._buf = b""
            self._send_raw(bytes([FXP_INIT]) + struct.pack("!I", 3))
            kind, body = self._recv_sftp()
            if kind != FXP_VERSION:
                raise SFTPError("server did not answer INIT")
        except BaseException:
            sock.close()
            self._transport = None
            raise
        if self.logger is not None:
            self.logger.info("connected to sftp", host=self.host,
                             port=self.port, user=self.username)

    def close(self) -> None:
        if self._transport is not None:
            try:
                self._transport.sock.close()
            except OSError:
                pass
            self._transport = None

    def _send_raw(self, sftp_packet: bytes) -> None:
        assert self._transport is not None
        self._transport.send_channel_data(
            self._channel, struct.pack("!I", len(sftp_packet))
            + sftp_packet)

    def _recv_sftp(self) -> tuple[int, bytes]:
        assert self._transport is not None
        while True:
            if len(self._buf) >= 4:
                (length,) = struct.unpack("!I", self._buf[:4])
                if len(self._buf) >= 4 + length:
                    body = self._buf[4:4 + length]
                    self._buf = self._buf[4 + length:]
                    return body[0], body[1:]
            self._buf += self._transport.recv_channel_data()

    def _request(self, kind: int, payload: bytes) -> tuple[int, Reader]:
        with self._lock:
            if self._transport is None:
                raise SFTPError("not connected; call connect() first")
            self._ids += 1
            req_id = self._ids
            try:
                self._send_raw(bytes([kind]) + struct.pack("!I", req_id)
                               + payload)
                while True:
                    rkind, body = self._recv_sftp()
                    r = Reader(body)
                    if r.uint32() == req_id:
                        return rkind, r
            except (OSError, TimeoutError, SSHError) as exc:
                self.close()  # poisoned stream: responses would pair
                raise SFTPError(                 # with the next request
                    f"connection lost mid-request ({exc})") from exc

    @staticmethod
    def _status(r: Reader) -> tuple[int, str]:
        code = r.uint32()
        message = r.text() if r.off < len(r.data) else ""
        return code, message

    def _expect_ok(self, kind: int, r: Reader, what: str) -> None:
        if kind != FXP_STATUS:
            raise SFTPError(f"{what}: unexpected reply {kind}")
        code, message = self._status(r)
        if code != FX_OK:
            raise SFTPError(f"{what}: {message or code}", code=code)

    def _open(self, path: str, pflags: int) -> bytes:
        kind, r = self._request(
            FXP_OPEN, ss(path) + struct.pack("!I", pflags)
            + struct.pack("!I", 0))
        if kind == FXP_HANDLE:
            return r.string()
        code, message = self._status(r)
        raise SFTPError(f"open {path}: {message or code}", code=code)

    def _close_handle(self, handle: bytes) -> None:
        kind, r = self._request(FXP_CLOSE, sb(handle))
        self._expect_ok(kind, r, "close")

    # ------------------------------------------------- FileSystem verbs
    def create(self, path: str, data: bytes | str = b"") -> None:
        payload = data.encode() if isinstance(data, str) else bytes(data)

        def op():
            handle = self._open(path, PFLAG_WRITE | PFLAG_CREAT
                                | PFLAG_TRUNC)
            try:
                for off in range(0, len(payload), _CHUNK) or [0]:
                    chunk = payload[off:off + _CHUNK]
                    kind, r = self._request(
                        FXP_WRITE, sb(handle) + struct.pack("!Q", off)
                        + sb(chunk))
                    self._expect_ok(kind, r, f"write {path}")
            finally:
                self._close_handle(handle)
        self._observed("CREATE", path, op)

    def read(self, path: str) -> bytes:
        def op():
            handle = self._open(path, PFLAG_READ)
            out = io.BytesIO()
            try:
                offset = 0
                while True:
                    kind, r = self._request(
                        FXP_READ, sb(handle) + struct.pack("!QI", offset,
                                                           _CHUNK))
                    if kind == FXP_STATUS:
                        code, message = self._status(r)
                        if code == FX_EOF:
                            return out.getvalue()
                        raise SFTPError(f"read {path}: {message or code}",
                                        code=code)
                    data = r.string()
                    out.write(data)
                    offset += len(data)
            finally:
                self._close_handle(handle)
        return self._observed("READ", path, op)

    def read_text(self, path: str) -> str:
        return self.read(path).decode()

    def append(self, path: str, data: bytes | str) -> None:
        payload = data.encode() if isinstance(data, str) else bytes(data)

        def op():
            try:
                size = self.stat(path).size
            except SFTPError:
                size = 0
            handle = self._open(path, PFLAG_WRITE | PFLAG_CREAT
                                | PFLAG_APPEND)
            try:
                kind, r = self._request(
                    FXP_WRITE, sb(handle) + struct.pack("!Q", size)
                    + sb(payload))
                self._expect_ok(kind, r, f"append {path}")
            finally:
                self._close_handle(handle)
        self._observed("APPEND", path, op)

    def remove(self, path: str) -> None:
        def op():
            kind, r = self._request(FXP_REMOVE, ss(path))
            self._expect_ok(kind, r, f"remove {path}")
        self._observed("REMOVE", path, op)

    def rename(self, old: str, new: str) -> None:
        def op():
            kind, r = self._request(FXP_RENAME, ss(old) + ss(new))
            self._expect_ok(kind, r, f"rename {old}")
        self._observed("RENAME", f"{old}->{new}", op)

    def stat(self, path: str) -> FileInfo:
        def op():
            kind, r = self._request(FXP_STAT, ss(path))
            if kind != FXP_ATTRS:
                code, message = self._status(r)
                raise SFTPError(f"stat {path}: {message or code}",
                                code=code)
            size, is_dir, mtime = _parse_attrs(r)
            return FileInfo(name=posixpath.basename(path) or path,
                            size=size, is_dir=is_dir, mod_time=mtime)
        return self._observed("STAT", path, op)

    def exists(self, path: str) -> bool:
        try:
            self.stat(path)
            return True
        except SFTPError:
            return False

    def mkdir(self, path: str) -> None:
        def op():
            kind, r = self._request(
                FXP_MKDIR, ss(path) + struct.pack("!I", 0))
            self._expect_ok(kind, r, f"mkdir {path}")
        self._observed("MKDIR", path, op)

    def rmdir(self, path: str) -> None:
        def op():
            kind, r = self._request(FXP_RMDIR, ss(path))
            self._expect_ok(kind, r, f"rmdir {path}")
        self._observed("RMDIR", path, op)

    def read_dir(self, path: str = ".") -> list[FileInfo]:
        def op():
            kind, r = self._request(FXP_OPENDIR, ss(path))
            if kind != FXP_HANDLE:
                code, message = self._status(r)
                raise SFTPError(f"opendir {path}: {message or code}",
                                code=code)
            handle = r.string()
            out: list[FileInfo] = []
            try:
                while True:
                    kind, r2 = self._request(FXP_READDIR, sb(handle))
                    if kind == FXP_STATUS:
                        code, _ = self._status(r2)
                        if code == FX_EOF:
                            break
                        raise SFTPError(f"readdir {path}: {code}",
                                        code=code)
                    for _ in range(r2.uint32()):
                        name = r2.text()
                        r2.text()  # longname
                        size, is_dir, mtime = _parse_attrs(r2)
                        if name not in (".", ".."):
                            out.append(FileInfo(name=name, size=size,
                                                is_dir=is_dir,
                                                mod_time=mtime))
            finally:
                self._close_handle(handle)
            return sorted(out, key=lambda f: f.name)
        return self._observed("READ_DIR", path, op)

    def read_rows(self, path: str, kind: str | None = None) -> RowReader:
        return RowReader(self.read_text(path),
                         kind or ("csv" if path.endswith(".csv")
                                  else "json"))

    # -------------------------------------- paramiko-style alias verbs
    # (what ftp.SFTPFileSystem accepts as an injected client)
    def putfo(self, fileobj: Any, path: str) -> None:
        self.create(path, fileobj.read())

    def getfo(self, path: str, fileobj: Any) -> None:
        fileobj.write(self.read(path))

    def listdir(self, path: str = ".") -> list[str]:
        return [f.name for f in self.read_dir(path)]

    def health_check(self) -> dict[str, Any]:
        try:
            self.read_dir("/")
            return {"status": "UP",
                    "details": {"host": self.host, "port": self.port,
                                "user": self.username}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------ mini server

class _SFTPSession:
    """One authenticated channel's SFTP state over a jailed root."""

    def __init__(self, root: Path) -> None:
        self.root = root
        self.handles: dict[bytes, Any] = {}
        self.dir_handles: dict[bytes, list[Path]] = {}
        self.dir_sent: dict[bytes, bool] = {}
        self._n = 0

    def resolve(self, path: str) -> Path:
        clean = posixpath.normpath("/" + path.replace("\\", "/"))
        return (self.root / clean.lstrip("/")).resolve() \
            if clean != "/" else self.root

    def _jailed(self, path: str) -> Path:
        target = self.resolve(path)
        # is_relative_to, not startswith: /srv/jail2 must not pass a
        # /srv/jail jail, and resolve() already chased symlinks
        if target != self.root and not target.is_relative_to(self.root):
            raise SFTPError("outside root", code=FX_PERMISSION_DENIED)
        return target

    def new_handle(self) -> bytes:
        self._n += 1
        return b"h%d" % self._n

    # one SFTP request -> one response packet (without length prefix)
    def handle_packet(self, kind: int, body: bytes) -> bytes:  # noqa: C901
        r = Reader(body)
        req_id = r.uint32()

        def status(code: int, message: str = "") -> bytes:
            return bytes([FXP_STATUS]) + struct.pack("!I", req_id) \
                + struct.pack("!I", code) + ss(message) + ss("en")

        try:
            if kind == FXP_OPEN:
                path = self._jailed(r.text())
                pflags = r.uint32()
                if pflags & PFLAG_WRITE:
                    mode = "r+b" if not (pflags & PFLAG_TRUNC) else "wb"
                    if not path.exists():
                        if not pflags & PFLAG_CREAT:
                            return status(FX_NO_SUCH_FILE, "no such file")
                        mode = "wb"
                    elif pflags & PFLAG_APPEND:
                        mode = "r+b"
                else:
                    if not path.exists():
                        return status(FX_NO_SUCH_FILE, "no such file")
                    mode = "rb"
                handle = self.new_handle()
                self.handles[handle] = path.open(mode)
                return bytes([FXP_HANDLE]) + struct.pack("!I", req_id) \
                    + sb(handle)
            if kind == FXP_CLOSE:
                handle = r.string()
                fh = self.handles.pop(handle, None)
                if fh is not None:
                    fh.close()
                self.dir_handles.pop(handle, None)
                self.dir_sent.pop(handle, None)
                return status(FX_OK)
            if kind == FXP_READ:
                fh = self.handles.get(r.string())
                if fh is None:  # stale/forged handle: per-request error
                    return status(FX_FAILURE, "bad handle")
                offset = r.uint64()
                length = r.uint32()
                fh.seek(offset)
                data = fh.read(length)
                if not data:
                    return status(FX_EOF, "eof")
                return bytes([FXP_DATA]) + struct.pack("!I", req_id) \
                    + sb(data)
            if kind == FXP_WRITE:
                fh = self.handles.get(r.string())
                if fh is None:
                    return status(FX_FAILURE, "bad handle")
                offset = r.uint64()
                data = r.string()
                fh.seek(offset)
                fh.write(data)
                return status(FX_OK)
            if kind in (FXP_STAT, FXP_LSTAT):
                path = self._jailed(r.text())
                if not path.exists():
                    return status(FX_NO_SUCH_FILE, "no such file")
                st = path.stat()
                return bytes([FXP_ATTRS]) + struct.pack("!I", req_id) \
                    + _attrs(st.st_size, path.is_dir(), st.st_mtime)
            if kind == FXP_OPENDIR:
                path = self._jailed(r.text())
                if not path.is_dir():
                    return status(FX_NO_SUCH_FILE, "not a directory")
                handle = self.new_handle()
                self.dir_handles[handle] = sorted(path.iterdir())
                self.dir_sent[handle] = False
                return bytes([FXP_HANDLE]) + struct.pack("!I", req_id) \
                    + sb(handle)
            if kind == FXP_READDIR:
                handle = r.string()
                if handle not in self.dir_handles:
                    return status(FX_FAILURE, "bad handle")
                if self.dir_sent[handle]:
                    return status(FX_EOF, "eof")
                self.dir_sent[handle] = True
                entries = self.dir_handles[handle]
                out = bytes([FXP_NAME]) + struct.pack(
                    "!II", req_id, len(entries))
                for entry in entries:
                    st = entry.stat()
                    out += ss(entry.name) + ss(entry.name) \
                        + _attrs(st.st_size, entry.is_dir(), st.st_mtime)
                return out
            if kind == FXP_REMOVE:
                path = self._jailed(r.text())
                if not path.is_file():
                    return status(FX_NO_SUCH_FILE, "no such file")
                path.unlink()
                return status(FX_OK)
            if kind == FXP_RENAME:
                old = self._jailed(r.text())
                new = self._jailed(r.text())
                if not old.exists():
                    return status(FX_NO_SUCH_FILE, "no such file")
                old.rename(new)
                return status(FX_OK)
            if kind == FXP_MKDIR:
                self._jailed(r.text()).mkdir(parents=False,
                                             exist_ok=False)
                return status(FX_OK)
            if kind == FXP_RMDIR:
                path = self._jailed(r.text())
                if not path.is_dir():
                    return status(FX_NO_SUCH_FILE, "no such dir")
                path.rmdir()
                return status(FX_OK)
        except SFTPError as exc:
            return status(exc.code, str(exc))
        except OSError as exc:
            return status(FX_FAILURE, str(exc))
        return status(FX_FAILURE, f"unsupported request {kind}")


class _SSHHandler(socketserver.BaseRequestHandler):
    @property
    def mini(self) -> "MiniSFTPServer":
        return self.server.mini  # type: ignore[attr-defined]

    def handle(self) -> None:
        transport = SSHServerTransport(self.request,
                                       host_key=self.mini.host_key,
                                       users=self.mini.users)
        try:
            transport.handshake()
            channel, subsystem = transport.accept_subsystem()
            if subsystem != "sftp":
                return
            session = _SFTPSession(self.mini.root)
            buf = b""
            # INIT/VERSION then the request loop
            while True:
                chunk = transport.recv_channel_data()
                # replenish the client's send window as we consume —
                # without this, uploads stall once the initial window
                # (1 GiB) is spent on a long-lived connection
                from .ssh_transport import MSG_CHANNEL_WINDOW_ADJUST
                transport.send_packet(
                    bytes([MSG_CHANNEL_WINDOW_ADJUST])
                    + struct.pack("!II", channel, len(chunk)))
                buf += chunk
                while len(buf) >= 4:
                    (length,) = struct.unpack("!I", buf[:4])
                    if len(buf) < 4 + length:
                        break
                    body = buf[4:4 + length]
                    buf = buf[4 + length:]
                    kind = body[0]
                    if kind == FXP_INIT:
                        reply = bytes([FXP_VERSION]) + struct.pack("!I", 3)
                    else:
                        reply = session.handle_packet(kind, body[1:])
                    transport.send_channel_data(
                        channel, struct.pack("!I", len(reply)) + reply)
        except (SSHError, SSHAuthError, ConnectionError, OSError):
            return


class MiniSFTPServer:
    """A real SSH server (from-spec transport, verified password auth,
    ed25519 host key) serving SFTP v3 out of a jailed directory."""

    def __init__(self, root: str | Path, host: str = "127.0.0.1",
                 port: int = 0, *, users: dict[str, str] | None = None
                 ) -> None:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey)
        self.root = Path(root).resolve()
        self.root.mkdir(parents=True, exist_ok=True)
        self.host = host
        self.port = port
        self.users = dict(users or {"demo": "demo"})
        self.host_key = Ed25519PrivateKey.generate()
        self._server: socketserver.ThreadingTCPServer | None = None
        self._thread: threading.Thread | None = None

    def host_public_key(self) -> bytes:
        from cryptography.hazmat.primitives import serialization
        return self.host_key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)

    def start(self) -> None:
        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = TCP((self.host, self.port), _SSHHandler)
        self._server.mini = self  # the handler reads this back
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="mini-sftp")
        self._thread.start()

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
