"""Elasticsearch network client speaking the REST API, plus a mini
server.

The reference's Elasticsearch module is a driver-backed network client
(container/datasources.go:708-746 over go-elasticsearch). This client
speaks the database's HTTP surface directly — ``PUT /{index}/_doc/{id}``,
``GET /{index}/_doc/{id}``, ``POST /{index}/_search`` with the query
DSL, ``POST /_bulk`` with NDJSON — behind the same method surface as
the embedded :class:`~gofr_tpu.datasource.document.Elasticsearch`
adapter, so swapping is a constructor change.

:class:`MiniESServer` serves the same endpoints over the embedded
adapter on the framework's HTTP server — the wire client and the
embedded engine share one search semantics by construction.
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Any, Iterable

from . import Instrumented
from ._http import json_call
from .document import DocumentEngine, DocumentError, DocumentNotFound, \
    Elasticsearch
from .miniserver import ThreadedHTTPMiniServer


class ESWireError(DocumentError):
    pass


class ElasticsearchWire(Instrumented):
    """REST client with the embedded adapter's verbs
    (index/get/delete/search/bulk)."""

    metric = "app_elasticsearch_stats"
    log_tag = "ES"

    def __init__(self, *, endpoint: str = "http://localhost:9200",
                 timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to elasticsearch",
                             endpoint=self.endpoint)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, method: str, path: str, body: Any = None,
              *, ndjson: str | None = None) -> tuple[int, dict]:
        if ndjson is not None:
            status, data = json_call(
                self.endpoint, method, path, raw_body=ndjson.encode(),
                headers={"Content-Type": "application/x-ndjson"},
                timeout_s=self.timeout_s)
        else:
            status, data = json_call(self.endpoint, method, path,
                                     body=body, timeout_s=self.timeout_s)
        return status, data if isinstance(data, dict) else {}

    @staticmethod
    def _doc_path(index: str, doc_id: Any) -> str:
        return (f"/{urllib.parse.quote(index, safe='')}/_doc/"
                f"{urllib.parse.quote(str(doc_id), safe='')}")

    # ----------------------------------------------------- native verbs
    def index(self, index: str, doc_id: Any, document: dict) -> None:
        def op():
            status, data = self._call(
                "PUT", self._doc_path(index, doc_id), body=document)
            if status not in (200, 201):
                raise ESWireError(f"index -> {status}: {data}")
        self._observed("INDEX", index, op)

    def get(self, index: str, doc_id: Any) -> dict:
        def op():
            status, data = self._call(
                "GET", self._doc_path(index, doc_id))
            if status == 404:
                raise DocumentNotFound(f"{index}/{doc_id}")
            if status != 200:
                raise ESWireError(f"get -> {status}: {data}")
            source = dict(data.get("_source", {}))
            source["_id"] = data.get("_id", doc_id)
            return source
        return self._observed("GET", index, op)

    def delete(self, index: str, doc_id: Any) -> None:
        def op():
            status, data = self._call(
                "DELETE", self._doc_path(index, doc_id))
            if status == 404:
                raise DocumentNotFound(f"{index}/{doc_id}")
            if status != 200:
                raise ESWireError(f"delete -> {status}: {data}")
        self._observed("DELETE", index, op)

    def search(self, index: str, query: dict | None = None,
               size: int = 10) -> dict:
        def op():
            body = {"size": size}
            if query is not None:
                body["query"] = query
            status, data = self._call(
                "POST", f"/{urllib.parse.quote(index)}/_search", body=body)
            if status != 200:
                raise ESWireError(f"search -> {status}: {data}")
            return data
        return self._observed("SEARCH", index, op)

    def bulk(self, index: str, documents: Iterable[tuple[Any, dict]]) -> int:
        docs = list(documents)

        def op():
            lines = []
            for doc_id, doc in docs:
                lines.append(json.dumps(
                    {"index": {"_index": index, "_id": doc_id}}))
                lines.append(json.dumps(doc))
            status, data = self._call("POST", "/_bulk",
                                      ndjson="\n".join(lines) + "\n")
            if status != 200 or data.get("errors"):
                raise ESWireError(f"bulk -> {status}: {data}")
            return len(docs)
        return self._observed("BULK", index, op)

    def health_check(self) -> dict[str, Any]:
        try:
            status, data = self._call("GET", "/_cluster/health")
            up = status == 200 and data.get("status") in ("green", "yellow")
            return {"status": "UP" if up else "DOWN",
                    "details": {"endpoint": self.endpoint,
                                "cluster_status": data.get("status")}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

class MiniESServer(ThreadedHTTPMiniServer):
    """The Elasticsearch REST surface over the embedded adapter —
    search semantics are shared with the in-process backend by
    delegation, not reimplementation."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(host, port)
        self.store = Elasticsearch(DocumentEngine())

    def handle(self, request) -> tuple[int, bytes, str]:
        try:
            return self._route(request)
        except DocumentNotFound:
            return 404, b'{"found": false}', "application/json"
        except DocumentError as exc:
            return 400, json.dumps(
                {"error": str(exc)}).encode(), "application/json"

    def _route(self, request) -> tuple[int, bytes, str]:
        parts = [p for p in request.path.split("/") if p]
        if request.path == "/_cluster/health":
            return 200, b'{"status": "green"}', "application/json"
        if parts and parts[0] == "_bulk":
            return self._bulk(request.body)
        if len(parts) == 2 and parts[1] == "_search":
            body = json.loads(request.body or b"{}")
            result = self.store.search(parts[0], body.get("query"),
                                       size=int(body.get("size", 10)))
            return 200, json.dumps(result).encode(), "application/json"
        if len(parts) == 3 and parts[1] == "_doc":
            index, doc_id = parts[0], parts[2]
            if request.method == "PUT":
                created = True
                try:
                    self.store.get(index, doc_id)
                    created = False
                except DocumentNotFound:
                    pass
                self.store.index(index, doc_id,
                                 json.loads(request.body or b"{}"))
                return (201 if created else 200), json.dumps(
                    {"_index": index, "_id": doc_id,
                     "result": "created" if created else "updated"}
                ).encode(), "application/json"
            if request.method == "GET":
                doc = self.store.get(index, doc_id)
                source = {k: v for k, v in doc.items() if k != "_id"}
                return 200, json.dumps(
                    {"_index": index, "_id": doc_id, "found": True,
                     "_source": source}).encode(), "application/json"
            if request.method == "DELETE":
                self.store.get(index, doc_id)  # 404 when absent
                self.store.delete(index, doc_id)
                return 200, json.dumps(
                    {"_id": doc_id, "result": "deleted"}
                ).encode(), "application/json"
        return 400, b'{"error": "unsupported route"}', "application/json"

    def _bulk(self, body: bytes) -> tuple[int, bytes, str]:
        lines = [ln for ln in body.decode().splitlines() if ln.strip()]
        items = []
        i = 0
        while i < len(lines):
            action = json.loads(lines[i])
            if "index" not in action:
                return 400, b'{"error": "only index actions supported"}', \
                    "application/json"
            meta = action["index"]
            doc = json.loads(lines[i + 1])
            self.store.index(meta["_index"], meta["_id"], doc)
            items.append({"index": {"_id": meta["_id"], "status": 200}})
            i += 2
        return 200, json.dumps(
            {"errors": False, "items": items}).encode(), "application/json"
