"""Key-value store interface + embedded backends.

The analog of reference ``datasource/kv-store`` (badger/dynamodb/nats
modules behind the container's ``KVStore`` interface,
container/datasources.go:366-378): ``get``/``set``/``delete`` plus
health. Two embedded backends ship — in-memory (tests, caches) and
sqlite-file (the badger-analog: a persistent single-file store).
Every op records into ``app_kv_stats``.
"""

from __future__ import annotations

import sqlite3
import threading
import time
from typing import Any

from . import ProviderMixin


class KVError(Exception):
    pass


class KeyNotFound(KVError):
    def __init__(self, key: str) -> None:
        super().__init__(f"key not found: {key}")
        self.key = key


class _Instrumented(ProviderMixin):
    def _observed(self, op: str, key: str, fn):
        start = time.perf_counter()
        try:
            return fn()
        finally:
            micros = int((time.perf_counter() - start) * 1e6)
            if self.logger is not None:
                self.logger.debug(f"KV {micros:6d}µs {op} {key}")
            if self.metrics is not None:
                self.metrics.record_histogram("app_kv_stats", micros / 1e6,
                                              type=op.lower())


class InMemoryKV(_Instrumented):
    """Dict-backed store — the mock/test backend."""

    def __init__(self) -> None:
        self._data: dict[str, str] = {}
        self._lock = threading.RLock()

    def connect(self) -> None:
        pass

    def get(self, key: str) -> str:
        def op():
            with self._lock:
                if key not in self._data:
                    raise KeyNotFound(key)
                return self._data[key]
        return self._observed("GET", key, op)

    def set(self, key: str, value: str) -> None:
        def op():
            with self._lock:
                self._data[key] = value
        return self._observed("SET", key, op)

    def delete(self, key: str) -> None:
        def op():
            with self._lock:
                self._data.pop(key, None)
        return self._observed("DELETE", key, op)

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._data)

    def health_check(self) -> dict[str, Any]:
        return {"status": "UP", "details": {"backend": "memory",
                                             "keys": len(self._data)}}

    def close(self) -> None:
        pass


class FileKV(_Instrumented):
    """Single-file persistent store (badger analog) over sqlite."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._conn: sqlite3.Connection | None = None
        self._lock = threading.RLock()

    def connect(self) -> None:
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v TEXT)")
        self._conn.commit()
        if self.logger is not None:
            self.logger.info("opened KV store", path=self.path)

    def _require(self) -> sqlite3.Connection:
        if self._conn is None:
            raise KVError("KV store not connected")
        return self._conn

    def get(self, key: str) -> str:
        def op():
            with self._lock:
                row = self._require().execute(
                    "SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
            if row is None:
                raise KeyNotFound(key)
            return row[0]
        return self._observed("GET", key, op)

    def set(self, key: str, value: str) -> None:
        def op():
            with self._lock:
                conn = self._require()
                conn.execute(
                    "INSERT INTO kv (k, v) VALUES (?, ?) "
                    "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                    (key, value))
                conn.commit()
        return self._observed("SET", key, op)

    def delete(self, key: str) -> None:
        def op():
            with self._lock:
                conn = self._require()
                conn.execute("DELETE FROM kv WHERE k = ?", (key,))
                conn.commit()
        return self._observed("DELETE", key, op)

    def keys(self) -> list[str]:
        with self._lock:
            rows = self._require().execute(
                "SELECT k FROM kv ORDER BY k").fetchall()
        return [r[0] for r in rows]

    def health_check(self) -> dict[str, Any]:
        try:
            with self._lock:
                n = self._require().execute(
                    "SELECT COUNT(*) FROM kv").fetchone()[0]
            return {"status": "UP", "details": {"backend": "file",
                                                 "path": self.path,
                                                 "keys": n}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
