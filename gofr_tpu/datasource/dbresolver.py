"""SQL primary/replica resolver.

The analog of reference ``datasource/dbresolver`` (resolver.go:21-50):
reads route to replicas under a selection strategy, writes always hit
the primary, each replica carries its own circuit breaker so a sick
replica drops out of rotation and probes back in, and a context switch
(``primary_reads``) pins reads to the primary for read-after-write
consistency. Per-target counters mirror the reference's atomic stats.
"""

from __future__ import annotations

import contextvars
import itertools
import random
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator, Sequence

STRATEGY_ROUND_ROBIN = "round_robin"
STRATEGY_RANDOM = "random"

_FORCE_PRIMARY: contextvars.ContextVar[bool] = \
    contextvars.ContextVar("gofr_dbresolver_primary", default=False)

_WRITE_PREFIXES = ("insert", "update", "delete", "create", "drop",
                   "alter", "replace", "truncate", "pragma")


@contextmanager
def primary_reads() -> Iterator[None]:
    """Pin reads inside the block to the primary (reference
    dbresolver PrimaryRoutes context keys)."""
    token = _FORCE_PRIMARY.set(True)
    try:
        yield
    finally:
        _FORCE_PRIMARY.reset(token)


class _ReplicaBreaker:
    """Per-replica circuit breaker (reference dbresolver/resolver.go:21-50):
    opens after ``threshold`` consecutive failures, half-opens after
    ``recovery_interval`` seconds to let one probe through."""

    def __init__(self, threshold: int = 3,
                 recovery_interval: float = 10.0) -> None:
        self.threshold = threshold
        self.recovery_interval = recovery_interval
        self.failures = 0
        self.opened_at: float | None = None
        self._lock = threading.Lock()

    def available(self) -> bool:
        with self._lock:
            if self.opened_at is None:
                return True
            if time.monotonic() - self.opened_at >= self.recovery_interval:
                return True  # half-open: admit a probe
            return False

    def record(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.failures = 0
                self.opened_at = None
            else:
                self.failures += 1
                if self.failures >= self.threshold:
                    self.opened_at = time.monotonic()


class DBResolver:
    """Routes `query`/`exec` over a primary + replicas, quacking like
    :class:`gofr_tpu.datasource.sql.SQL` so it drops into the
    container's ``sql`` slot unchanged."""

    def __init__(self, primary: Any, replicas: Sequence[Any] = (),
                 *, strategy: str = STRATEGY_ROUND_ROBIN,
                 breaker_threshold: int = 3,
                 breaker_recovery: float = 10.0) -> None:
        if strategy not in (STRATEGY_ROUND_ROBIN, STRATEGY_RANDOM):
            raise ValueError(f"unknown strategy {strategy!r}")
        self.primary = primary
        self.replicas = list(replicas)
        self.strategy = strategy
        self._rr = itertools.count()
        self._breakers = [
            _ReplicaBreaker(breaker_threshold, breaker_recovery)
            for _ in self.replicas]
        self.stats = {"primary_reads": 0, "replica_reads": 0,
                      "writes": 0, "replica_failovers": 0}
        self._stats_lock = threading.Lock()

    # ------------------------------------------------------ provider API
    def use_logger(self, logger: Any) -> None:
        for db in (self.primary, *self.replicas):
            db.use_logger(logger)

    def use_metrics(self, metrics: Any) -> None:
        for db in (self.primary, *self.replicas):
            db.use_metrics(metrics)

    def use_tracer(self, tracer: Any) -> None:
        for db in (self.primary, *self.replicas):
            db.use_tracer(tracer)

    def connect(self) -> None:
        for db in (self.primary, *self.replicas):
            db.connect()

    # ---------------------------------------------------------- routing
    def _bump(self, key: str) -> None:
        with self._stats_lock:
            self.stats[key] += 1

    def _pick_replica(self) -> int | None:
        live = [i for i, b in enumerate(self._breakers) if b.available()]
        if not live:
            return None
        if self.strategy == STRATEGY_RANDOM:
            return random.choice(live)
        return live[next(self._rr) % len(live)]

    def _is_write(self, query: str) -> bool:
        head = query.lstrip().split(None, 1)
        return bool(head) and head[0].lower() in _WRITE_PREFIXES

    def query(self, query: str, *args: Any) -> Any:
        if self._is_write(query) or not self.replicas \
                or _FORCE_PRIMARY.get():
            self._bump("primary_reads")
            return self.primary.query(query, *args)
        idx = self._pick_replica()
        if idx is None:
            # every replica's breaker is open: fall back to primary
            self._bump("replica_failovers")
            self._bump("primary_reads")
            return self.primary.query(query, *args)
        try:
            rows = self.replicas[idx].query(query, *args)
            self._breakers[idx].record(True)
            self._bump("replica_reads")
            return rows
        except Exception:
            self._breakers[idx].record(False)
            self._bump("replica_failovers")
            self._bump("primary_reads")
            return self.primary.query(query, *args)

    def query_row(self, query: str, *args: Any) -> Any:
        rows = self.query(query, *args)
        return rows[0] if rows else None

    def exec(self, query: str, *args: Any) -> Any:
        self._bump("writes")
        return self.primary.exec(query, *args)

    def select(self, entity_type: type, query: str, *args: Any) -> Any:
        # route through the resolver, then map on the primary's helper
        # semantics (all SQL backends share the dataclass mapping)
        rows = self.query(query, *args)
        from dataclasses import fields, is_dataclass
        if not is_dataclass(entity_type):
            from .sql import SQLError
            raise SQLError("select requires a dataclass type")
        names = [f.name for f in fields(entity_type)]
        return [entity_type(**{n: row[n] for n in names
                               if n in set(row.keys())})
                for row in rows]

    def begin(self):
        # transactions are writes by definition
        self._bump("writes")
        return self.primary.begin()

    # ------------------------------------------------------------ health
    def health_check(self) -> dict[str, Any]:
        primary_health = self.primary.health_check()
        replicas = [db.health_check() for db in self.replicas]
        status = primary_health.get("status", "DOWN")
        if status == "UP" and any(r.get("status") != "UP"
                                  for r in replicas):
            status = "DEGRADED"
        return {"status": status, "primary": primary_health,
                "replicas": replicas, "stats": dict(self.stats)}

    def close(self) -> None:
        for db in (self.primary, *self.replicas):
            db.close()
