"""Solr network client speaking the HTTP API, plus a mini server.

The reference's Solr module is an HTTP client over the Solr REST
surface (container/datasources.go:386-406, datasource/solr). This
client speaks that surface directly — ``POST /solr/{core}/update``
with JSON documents (add and delete commands),
``GET /solr/{core}/select?q=...&rows=...`` — behind the same method
surface as the embedded :class:`~gofr_tpu.datasource.document.Solr`
adapter, so swapping is a constructor change.

:class:`MiniSolrServer` serves those endpoints over the embedded
adapter on the framework's HTTP server, sharing search semantics with
the in-process backend by delegation.
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Any, Iterable

from . import Instrumented
from ._http import json_call
from .document import DocumentEngine, DocumentError, Solr
from .miniserver import ThreadedHTTPMiniServer


class SolrWireError(DocumentError):
    pass


class SolrWire(Instrumented):
    """HTTP client with the embedded adapter's verbs
    (add/search/delete)."""

    metric = "app_solr_stats"
    log_tag = "SOLR"

    def __init__(self, *, endpoint: str = "http://localhost:8983",
                 timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to solr", endpoint=self.endpoint)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, method: str, path: str,
              body: Any = None) -> tuple[int, dict]:
        status, data = json_call(self.endpoint, method, path, body=body,
                                 timeout_s=self.timeout_s)
        return status, data if isinstance(data, dict) else {}

    # ----------------------------------------------------- native verbs
    def add(self, core: str, documents: Iterable[dict]) -> int:
        docs = list(documents)

        def op():
            status, data = self._call(
                "POST",
                f"/solr/{urllib.parse.quote(core)}/update?commit=true",
                body=docs)
            if status != 200:
                raise SolrWireError(f"add -> {status}: {data}")
            return len(docs)
        return self._observed("ADD", core, op)

    def search(self, core: str, query: str, rows: int = 10) -> dict:
        def op():
            params = urllib.parse.urlencode({"q": query, "rows": rows,
                                             "wt": "json"})
            status, data = self._call(
                "GET", f"/solr/{urllib.parse.quote(core)}/select?{params}")
            if status != 200:
                raise SolrWireError(f"search -> {status}: {data}")
            return data
        return self._observed("SEARCH", core, op)

    def delete(self, core: str, doc_id: Any) -> None:
        def op():
            status, data = self._call(
                "POST",
                f"/solr/{urllib.parse.quote(core)}/update?commit=true",
                body={"delete": {"id": doc_id}})
            if status != 200:
                raise SolrWireError(f"delete -> {status}: {data}")
        self._observed("DELETE", core, op)

    def health_check(self) -> dict[str, Any]:
        try:
            status, data = self._call(
                "GET", "/solr/admin/info/system?wt=json")
            return {"status": "UP" if status == 200 else "DOWN",
                    "details": {"endpoint": self.endpoint,
                                "solr_version":
                                    data.get("lucene", {}).get(
                                        "solr-spec-version", "")}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

class MiniSolrServer(ThreadedHTTPMiniServer):
    """The Solr HTTP surface over the embedded adapter."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(host, port)
        self.store = Solr(DocumentEngine())

    def handle(self, request) -> tuple[int, bytes, str]:
        try:
            return self._route(request)
        except DocumentError as exc:
            return 400, json.dumps(
                {"error": str(exc)}).encode(), "application/json"

    def _route(self, request) -> tuple[int, bytes, str]:
        parts = [p for p in request.path.split("/") if p]
        if request.path.startswith("/solr/admin/info/system"):
            return 200, json.dumps(
                {"lucene": {"solr-spec-version": "9.0-mini"}}
            ).encode(), "application/json"
        if len(parts) == 3 and parts[0] == "solr":
            core, verb = parts[1], parts[2]
            if verb == "update" and request.method == "POST":
                body = json.loads(request.body or b"null")
                if isinstance(body, list):
                    self.store.add(core, body)
                    return 200, b'{"responseHeader": {"status": 0}}', \
                        "application/json"
                if isinstance(body, dict) and "delete" in body:
                    self.store.delete(core, body["delete"].get("id"))
                    return 200, b'{"responseHeader": {"status": 0}}', \
                        "application/json"
                return 400, b'{"error": "unsupported update body"}', \
                    "application/json"
            if verb == "select":
                query = request.param("q") or "*:*"
                rows = int(request.param("rows") or "10")
                result = self.store.search(core, query, rows=rows)
                result["responseHeader"] = {"status": 0}
                return 200, json.dumps(result).encode(), "application/json"
        return 400, b'{"error": "unsupported route"}', "application/json"
