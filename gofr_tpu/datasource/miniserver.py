"""Shared lifecycle for HTTP-protocol mini servers.

The Influx and S3 mini servers both serve an HTTP wire surface from
sync test code: this base runs the framework's asyncio
:class:`~gofr_tpu.http.server.HTTPServer` on a daemon thread so
blocking clients (urllib) can call it, with an idempotent close that
shuts the server down and stops the loop. Subclasses implement
:meth:`handle` returning ``(status, body bytes, content_type)``.
"""

from __future__ import annotations

import threading
from typing import Any


class ThreadedHTTPMiniServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self._loop: Any = None
        self._server: Any = None
        self._loop_thread: threading.Thread | None = None

    def handle(self, request) -> tuple[int, bytes, str]:  # pragma: no cover
        raise NotImplementedError

    def start(self) -> None:
        import asyncio

        from ..http.responder import ResponseData
        from ..http.server import HTTPServer

        async def handler(request) -> ResponseData:
            status, body, ctype = self.handle(request)
            return ResponseData(status=status, body=body,
                                content_type=ctype)

        ready = threading.Event()

        def run() -> None:
            loop = asyncio.new_event_loop()
            self._loop = loop
            server = HTTPServer(handler, host=self.host, port=self.port)
            loop.run_until_complete(server.start())
            self._server = server
            self.port = server.bound_port
            ready.set()
            loop.run_forever()

        self._loop_thread = threading.Thread(
            target=run, daemon=True, name=type(self).__name__)
        self._loop_thread.start()
        if not ready.wait(10):
            raise RuntimeError(f"{type(self).__name__} failed to start")

    def close(self) -> None:
        import asyncio
        if self._loop is None:
            return

        async def stop() -> None:
            if self._server is not None:
                await self._server.shutdown()

        try:
            asyncio.run_coroutine_threadsafe(stop(), self._loop) \
                .result(timeout=5)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
        self._loop = None  # double-close is a no-op
