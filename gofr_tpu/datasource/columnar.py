"""Columnar/CQL family: Cassandra-, ScyllaDB-, Clickhouse- and
Oracle-shaped stores over an embedded sqlite engine.

The reference's canonical interfaces live in container/datasources.go
(Cassandra :42 with batch/ctx variants :122-188, Clickhouse :196,
Oracle :210, ScyllaDB :600) and are backed by gocql/clickhouse-go/
go-ora drivers in their own modules. The statement surface of those
interfaces — ``query`` (select into destinations), ``exec`` (mutate),
``batch`` (atomic multi-statement) — is implemented here over sqlite,
whose SQL dialect covers the CQL/SQL subset those drivers speak; a
production deployment swaps the engine for a cluster client behind the
same interface.
"""

from __future__ import annotations

import re
import sqlite3
import threading
from typing import Any

from . import Instrumented


class ColumnarError(Exception):
    pass


class BatchNotInitialised(ColumnarError):
    def __init__(self, name: str) -> None:
        super().__init__(f"batch {name!r} not initialised; call new_batch")


_CQL_UNSUPPORTED = re.compile(
    r"\b(ALLOW\s+FILTERING|USING\s+TTL\s+\d+)\b", re.IGNORECASE)


class _CQLStore(Instrumented):
    """Cassandra-shaped statement API over sqlite (reference
    container/datasources.go:42-120; batch ops :122-188)."""

    backend_name = "cql"

    def __init__(self, keyspace: str = "default",
                 path: str = ":memory:") -> None:
        self.keyspace = keyspace
        self.path = path
        self._conn: sqlite3.Connection | None = None
        self._lock = threading.RLock()
        self._batches: dict[str, list[tuple[str, tuple]]] = {}

    def connect(self) -> None:
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        if self.logger is not None:
            self.logger.info(f"connected {self.backend_name}",
                             keyspace=self.keyspace)

    def _require(self) -> sqlite3.Connection:
        if self._conn is None:
            raise ColumnarError(f"{self.backend_name} not connected")
        return self._conn

    @staticmethod
    def _translate(stmt: str) -> str:
        # strip CQL-only clauses sqlite rejects so gocql-style statements run
        return _CQL_UNSUPPORTED.sub("", stmt).strip()

    # -- statement surface
    def query(self, stmt: str, *args: Any) -> list[dict]:
        """SELECT; rows come back as dicts (the reference scans into
        destination structs — dicts are the Python analog)."""
        def op():
            with self._lock:
                cur = self._require().execute(self._translate(stmt), args)
                return [dict(r) for r in cur.fetchall()]
        return self._observed("QUERY", stmt.split(None, 1)[0], op)

    def exec(self, stmt: str, *args: Any) -> None:
        def op():
            with self._lock:
                conn = self._require()
                conn.execute(self._translate(stmt), args)
                conn.commit()
        self._observed("EXEC", stmt.split(None, 1)[0], op)

    # context-variant aliases (reference WithContext methods :122-188)
    query_with_ctx = query
    exec_with_ctx = exec

    # -- batches (reference :146-188)
    def new_batch(self, name: str, _batch_type: int = 0) -> None:
        with self._lock:
            self._batches[name] = []

    def batch_query(self, name: str, stmt: str, *args: Any) -> None:
        with self._lock:
            if name not in self._batches:
                raise BatchNotInitialised(name)
            self._batches[name].append((self._translate(stmt), args))

    def execute_batch(self, name: str) -> None:
        def op():
            with self._lock:
                if name not in self._batches:
                    raise BatchNotInitialised(name)
                stmts = self._batches.pop(name)
                conn = self._require()
                try:
                    for stmt, args in stmts:
                        conn.execute(stmt, args)
                    conn.commit()
                except Exception:
                    conn.rollback()
                    raise
        self._observed("BATCH", name, op)

    def health_check(self) -> dict[str, Any]:
        try:
            with self._lock:
                self._require().execute("SELECT 1")
            return {"status": "UP", "details": {"backend": self.backend_name,
                                                "keyspace": self.keyspace}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None


class Cassandra(_CQLStore):
    metric = "app_cassandra_stats"
    log_tag = "CQL"
    backend_name = "cassandra"


class ScyllaDB(_CQLStore):
    """Same statement surface as Cassandra (reference
    container/datasources.go:600-635)."""

    metric = "app_scylladb_stats"
    log_tag = "SCYLLA"
    backend_name = "scylladb"


class Clickhouse(_CQLStore):
    """Clickhouse-shaped surface (reference container/datasources.go:196-208):
    exec / select-into / async-insert."""

    metric = "app_clickhouse_stats"
    log_tag = "CH"
    backend_name = "clickhouse"

    def select(self, stmt: str, *args: Any) -> list[dict]:
        return self.query(stmt, *args)

    def async_insert(self, stmt: str, *args: Any) -> None:
        # the embedded engine commits synchronously; the interface point
        # is fire-and-forget semantics, which exec satisfies
        self.exec(stmt, *args)


class Oracle(_CQLStore):
    """Oracle-shaped surface (reference container/datasources.go:210-230),
    including the transactional migration hook the oracle module adds
    (datasource/oracle/migration/migration.go:26). This is the
    embedded-engine variant; :mod:`.oracle_wire` is the network client
    (TNS transport + O5LOGON-style auth) with the same bar as the other
    wire clients."""

    metric = "app_oracle_stats"
    log_tag = "ORA"
    backend_name = "oracle"

    def select(self, stmt: str, *args: Any) -> list[dict]:
        return self.query(stmt, *args)

    def begin(self) -> "OracleTx":
        return OracleTx(self)


class OracleTx:
    """Explicit transaction wrapper used by migrations."""

    def __init__(self, store: Oracle) -> None:
        self._store = store
        self._stmts: list[tuple[str, tuple]] = []

    def exec(self, stmt: str, *args: Any) -> None:
        self._stmts.append((stmt, args))

    def commit(self) -> None:
        name = f"__tx_{id(self)}"
        self._store.new_batch(name)
        for stmt, args in self._stmts:
            self._store.batch_query(name, stmt, *args)
        self._store.execute_batch(name)
        self._stmts.clear()

    def rollback(self) -> None:
        self._stmts.clear()
