"""Shared JSON-over-HTTP request helper for the wire clients.

The ES/Solr/OpenTSDB/Arango clients all speak JSON REST; this is their
one urlopen + error-decode path, so timeout and error handling behave
identically across them.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any


def json_call(endpoint: str, method: str, path: str, *,
              body: Any = None, raw_body: bytes | None = None,
              headers: dict[str, str] | None = None,
              timeout_s: float = 30.0) -> tuple[int, Any]:
    """One request; -> (status, decoded JSON | text-fallback dict).

    ``body`` is JSON-encoded; ``raw_body`` is sent verbatim (callers
    set their own Content-Type via ``headers``).
    """
    send = {"Content-Type": "application/json"}
    send.update(headers or {})
    if raw_body is not None:
        data: bytes | None = raw_body
    elif body is not None:
        data = json.dumps(body).encode()
    else:
        data = None
    req = urllib.request.Request(endpoint + path, data=data, method=method,
                                 headers=send)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            payload = r.read()
            return r.status, (json.loads(payload) if payload else None)
    except urllib.error.HTTPError as exc:
        payload = exc.read()
        try:
            return exc.code, json.loads(payload or b"null")
        except json.JSONDecodeError:
            return exc.code, {"error": payload.decode("utf-8", "replace")}
