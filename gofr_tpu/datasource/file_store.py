"""FileSystem abstraction + local implementation.

The analog of reference ``datasource/file`` (interface.go:10-60,
local_fs.go, row_reader.go, observability.go:10-36): one interface over
local and remote stores (the reference ships azure/ftp/gcs/s3/sftp
behind it) so handler code is storage-agnostic. This build ships the
local FS; remote backends implement the same surface.

Ops are logged + timed into ``app_file_stats``; JSON/CSV row readers
mirror the reference's ``RowReader`` for line-oriented file parsing.
"""

from __future__ import annotations

import csv
import io
import json
import os
import shutil
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Iterator

from . import ProviderMixin


class FileError(Exception):
    pass


@dataclass
class FileInfo:
    """stat result (reference file/interface.go FileInfo)."""

    name: str
    size: int
    is_dir: bool
    mod_time: float


class RowReader:
    """Iterate structured rows out of a text payload
    (reference file/row_reader.go): JSON arrays/JSONL and CSV."""

    def __init__(self, text: str, kind: str) -> None:
        self._rows: list[Any] = []
        if kind == "json":
            stripped = text.strip()
            if stripped.startswith("["):
                self._rows = json.loads(stripped)
            else:
                self._rows = [json.loads(line)
                              for line in stripped.splitlines() if line.strip()]
        elif kind == "csv":
            self._rows = list(csv.DictReader(io.StringIO(text)))
        else:
            raise FileError(f"unsupported row format {kind!r}")

    def __iter__(self) -> Iterator[Any]:
        return iter(self._rows)

    def __len__(self) -> int:
        return len(self._rows)


class LocalFileSystem(ProviderMixin):
    """Local FS behind the FileSystem interface
    (reference file/local_fs.go)."""

    def __init__(self, root: str = ".") -> None:
        self.root = Path(root)

    def connect(self) -> None:
        self.root.mkdir(parents=True, exist_ok=True)

    def _observed(self, op: str, path: str, fn):
        start = time.perf_counter()
        status = "SUCCESS"
        try:
            return fn()
        except Exception:
            status = "ERROR"
            raise
        finally:
            micros = int((time.perf_counter() - start) * 1e6)
            if self.logger is not None:
                self.logger.debug(f"FILE {micros:6d}µs {op} {path} {status}")
            if self.metrics is not None:
                self.metrics.record_histogram("app_file_stats", micros / 1e6,
                                              type=op.lower(), status=status)

    def _resolve(self, path: str) -> Path:
        p = (self.root / path).resolve()
        root = self.root.resolve()
        if root != p and root not in p.parents:
            raise FileError(f"path escapes file-store root: {path!r}")
        return p

    # ------------------------------------------------------------- files
    def create(self, path: str, data: bytes | str = b"") -> None:
        def op():
            p = self._resolve(path)
            p.parent.mkdir(parents=True, exist_ok=True)
            mode = "w" if isinstance(data, str) else "wb"
            with open(p, mode) as f:
                f.write(data)
        return self._observed("CREATE", path, op)

    def read(self, path: str) -> bytes:
        def op():
            return self._resolve(path).read_bytes()
        return self._observed("READ", path, op)

    def read_text(self, path: str) -> str:
        return self.read(path).decode()

    def append(self, path: str, data: bytes | str) -> None:
        def op():
            mode = "a" if isinstance(data, str) else "ab"
            with open(self._resolve(path), mode) as f:
                f.write(data)
        return self._observed("APPEND", path, op)

    def remove(self, path: str) -> None:
        def op():
            os.remove(self._resolve(path))
        return self._observed("REMOVE", path, op)

    def rename(self, old: str, new: str) -> None:
        def op():
            os.rename(self._resolve(old), self._resolve(new))
        return self._observed("RENAME", f"{old}->{new}", op)

    def stat(self, path: str) -> FileInfo:
        def op():
            p = self._resolve(path)
            st = p.stat()
            return FileInfo(name=p.name, size=st.st_size,
                            is_dir=p.is_dir(), mod_time=st.st_mtime)
        return self._observed("STAT", path, op)

    def exists(self, path: str) -> bool:
        return self._resolve(path).exists()

    # ------------------------------------------------------- directories
    def mkdir(self, path: str) -> None:
        def op():
            self._resolve(path).mkdir(parents=True, exist_ok=True)
        return self._observed("MKDIR", path, op)

    def remove_all(self, path: str) -> None:
        def op():
            shutil.rmtree(self._resolve(path))
        return self._observed("REMOVEALL", path, op)

    def read_dir(self, path: str = ".") -> list[FileInfo]:
        def op():
            out = []
            for child in sorted(self._resolve(path).iterdir()):
                st = child.stat()
                out.append(FileInfo(name=child.name, size=st.st_size,
                                    is_dir=child.is_dir(),
                                    mod_time=st.st_mtime))
            return out
        return self._observed("READDIR", path, op)

    def glob(self, pattern: str) -> list[str]:
        def op():
            root = self.root.resolve()
            return sorted(str(p.relative_to(root))
                          for p in root.glob(pattern))
        return self._observed("GLOB", pattern, op)

    # --------------------------------------------------------- row reads
    def read_rows(self, path: str, kind: str | None = None) -> RowReader:
        """Parse a JSON/JSONL/CSV file into rows
        (reference file/row_reader.go)."""
        if kind is None:
            suffix = Path(path).suffix.lower().lstrip(".")
            kind = {"jsonl": "json"}.get(suffix, suffix)
        return RowReader(self.read_text(path), kind)

    # ------------------------------------------------------------ health
    def health_check(self) -> dict[str, Any]:
        try:
            usage = shutil.disk_usage(self.root)
            return {"status": "UP",
                    "details": {"root": str(self.root),
                                "free_bytes": usage.free}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        pass
