"""S3 network client speaking the REST API with real AWS SigV4
signing, plus a signature-verifying mini server.

The reference's S3 module is a driver-backed network client
(datasource/file/s3 over aws-sdk-go). This client speaks the S3 REST
surface directly — PUT/GET/DELETE object, ListObjectsV2 (XML),
bucket creation — and signs every request with AWS Signature
Version 4 implemented from the specification (canonical request →
string-to-sign → HMAC chain), so it talks to real S3/MinIO/localstack
endpoints unchanged.

:class:`MiniS3Server` is the hermetic stand-in on the framework's own
HTTP server over the embedded
:class:`~gofr_tpu.datasource.object_store.ObjectStoreEngine`. It
*verifies* each request's SigV4 signature against the configured
credentials — the tests prove the signing chain is real, not
decorative.
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import hmac
import threading
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Any

from . import Instrumented
from .miniserver import ThreadedHTTPMiniServer
from .object_store import ObjectNotFound, ObjectStoreEngine


class S3Error(Exception):
    pass


# ----------------------------------------------------------------- SigV4

def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _uri_encode(s: str, *, slash_ok: bool = False) -> str:
    safe = "-._~" + ("/" if slash_ok else "")
    return urllib.parse.quote(s, safe=safe)


def _canonical_query(query: dict[str, str]) -> str:
    """The one encoding both the signature and the URL must share —
    a single construction site so they byte-match by construction."""
    return "&".join(f"{_uri_encode(k)}={_uri_encode(v)}"
                    for k, v in sorted(query.items()))


def sign_v4(method: str, path: str, query: dict[str, str],
            headers: dict[str, str], payload: bytes, *,
            access_key: str, secret_key: str, region: str,
            service: str = "s3",
            when: _dt.datetime | None = None) -> dict[str, str]:
    """-> headers with Authorization/x-amz-date/x-amz-content-sha256
    added, per the SigV4 specification."""
    when = when or _dt.datetime.now(_dt.timezone.utc)
    amz_date = when.strftime("%Y%m%dT%H%M%SZ")
    scope_date = when.strftime("%Y%m%d")
    payload_hash = _sha256(payload)

    out = {k.lower(): v.strip() for k, v in headers.items()}
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash

    signed_names = sorted(out)
    canonical_headers = "".join(f"{k}:{out[k]}\n" for k in signed_names)
    signed_headers = ";".join(signed_names)
    canonical_query = _canonical_query(query)
    canonical_request = "\n".join([
        method, _uri_encode(path, slash_ok=True), canonical_query,
        canonical_headers, signed_headers, payload_hash])

    scope = f"{scope_date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        _sha256(canonical_request.encode())])

    key = _hmac(("AWS4" + secret_key).encode(), scope_date)
    key = _hmac(key, region)
    key = _hmac(key, service)
    key = _hmac(key, "aws4_request")
    signature = hmac.new(key, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()

    out["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}")
    return out


# ----------------------------------------------------------------- client

class S3Wire(Instrumented):
    """SigV4-signed S3 REST client with the embedded adapter's native
    verbs (put_object/get_object/delete_object/list_objects)."""

    metric = "app_s3_stats"
    log_tag = "S3"

    def __init__(self, *, endpoint: str = "http://localhost:9000",
                 bucket: str = "gofr", access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to S3", endpoint=self.endpoint,
                             bucket=self.bucket)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, method: str, path: str,
              query: dict[str, str] | None = None,
              body: bytes = b"") -> tuple[int, bytes]:
        query = query or {}
        host = urllib.parse.urlsplit(self.endpoint).netloc
        headers = sign_v4(method, path, query, {"host": host}, body,
                          access_key=self.access_key,
                          secret_key=self.secret_key, region=self.region)
        url = self.endpoint + _uri_encode(path, slash_ok=True)
        if query:
            url += "?" + _canonical_query(query)
        req = urllib.request.Request(url, data=body or None, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read()

    # ----------------------------------------------------- native verbs
    def create_bucket(self, bucket: str | None = None) -> None:
        name = bucket or self.bucket
        # AWS requires a LocationConstraint body outside us-east-1
        body = b""
        if self.region != "us-east-1":
            body = (
                "<CreateBucketConfiguration>"
                f"<LocationConstraint>{self.region}</LocationConstraint>"
                "</CreateBucketConfiguration>").encode()

        def op():
            status, data = self._call("PUT", f"/{name}", body=body)
            if status not in (200, 409):
                raise S3Error(f"create bucket -> {status}: {data[:200]!r}")
        self._observed("CREATE_BUCKET", name, op)

    def put_object(self, key: str, body: bytes) -> None:
        def op():
            status, data = self._call(
                "PUT", f"/{self.bucket}/{key}", body=body)
            if status != 200:
                raise S3Error(f"put {key} -> {status}: {data[:200]!r}")
        self._observed("PUT", key, op)

    def get_object(self, key: str) -> bytes:
        def op():
            status, data = self._call("GET", f"/{self.bucket}/{key}")
            if status == 404:
                raise ObjectNotFound(f"{self.bucket}/{key}")
            if status != 200:
                raise S3Error(f"get {key} -> {status}: {data[:200]!r}")
            return data
        return self._observed("GET", key, op)

    def delete_object(self, key: str) -> None:
        def op():
            status, data = self._call("DELETE", f"/{self.bucket}/{key}")
            if status not in (200, 204):
                raise S3Error(f"delete {key} -> {status}: {data[:200]!r}")
        self._observed("DELETE", key, op)

    def list_objects(self, prefix: str = "") -> list[dict]:
        def op():
            out: list[dict] = []
            token = ""
            while True:  # follow ListObjectsV2 pagination to the end
                query = {"list-type": "2", "prefix": prefix}
                if token:
                    query["continuation-token"] = token
                status, data = self._call("GET", f"/{self.bucket}",
                                          query=query)
                if status != 200:
                    raise S3Error(f"list -> {status}: {data[:200]!r}")
                root = ET.fromstring(data)
                ns = (root.tag.partition("}")[0] + "}"
                      if "}" in root.tag else "")
                # same dict shape as the embedded
                # S3FileSystem.list_objects (object_store.py) so
                # backend swaps never break callers
                for item in root.iter(f"{ns}Contents"):
                    out.append({
                        "Key": item.findtext(f"{ns}Key", ""),
                        "Size": int(item.findtext(f"{ns}Size", "0")),
                        "LastModified": item.findtext(
                            f"{ns}LastModified", "")})
                if root.findtext(f"{ns}IsTruncated", "false") != "true":
                    return out
                token = root.findtext(f"{ns}NextContinuationToken", "")
                if not token:
                    return out
        return self._observed("LIST", prefix or "*", op)

    def exists(self, key: str) -> bool:
        def op():
            status, data = self._call("HEAD", f"/{self.bucket}/{key}")
            if status == 200:
                return True
            if status == 404:
                return False
            # 403/5xx are auth or server trouble, not "object absent"
            raise S3Error(f"head {key} -> {status}: {data[:200]!r}")
        return self._observed("HEAD", key, op)

    def health_check(self) -> dict[str, Any]:
        try:
            status, _ = self._call("GET", f"/{self.bucket}",
                                   query={"list-type": "2",
                                          "max-keys": "0"})
            up = status in (200, 404)
            return {"status": "UP" if up else "DOWN",
                    "details": {"endpoint": self.endpoint,
                                "bucket": self.bucket}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

class MiniS3Server(ThreadedHTTPMiniServer):
    """S3 REST surface over the embedded ObjectStoreEngine, on the
    framework's HTTP server (lifecycle from
    :class:`~gofr_tpu.datasource.miniserver.ThreadedHTTPMiniServer`).
    Every request's SigV4 signature is re-derived and verified against
    the configured credentials — a wrong secret is a 403, exactly like
    real S3."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 access_key: str = "test", secret_key: str = "secret",
                 region: str = "us-east-1") -> None:
        super().__init__(host, port)
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.engine = ObjectStoreEngine()
        self.buckets: set[str] = set()
        self._lock = threading.Lock()

    def handle(self, request) -> tuple[int, bytes, str]:
        return self._route(request)

    # ----------------------------------------------------- verification
    def _verify(self, request) -> bool:
        auth = request.headers.get("authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            return False
        try:
            fields = dict(part.strip().split("=", 1)
                          for part in auth[17:].split(","))
            credential = fields["Credential"]
            signed_headers = fields["SignedHeaders"].split(";")
            got_signature = fields["Signature"]
            access_key, scope_date = credential.split("/")[:2]
        except (KeyError, ValueError):
            return False
        if access_key != self.access_key:
            return False
        headers = {name: request.headers.get(name, "")
                   for name in signed_headers}
        try:
            when = _dt.datetime.strptime(
                request.headers.get("x-amz-date", ""),
                "%Y%m%dT%H%M%SZ").replace(tzinfo=_dt.timezone.utc)
        except ValueError:  # missing/garbage date: bad auth, not a 500
            return False
        expect = sign_v4(
            request.method, request.path,
            {k: v[0] for k, v in request.query.items()},
            headers, request.body,
            access_key=self.access_key, secret_key=self.secret_key,
            region=self.region, when=when)
        expect_sig = expect["authorization"].rsplit("Signature=", 1)[-1]
        return hmac.compare_digest(expect_sig, got_signature)

    # ----------------------------------------------------------- routing
    def _route(self, request) -> tuple[int, bytes, str]:
        if not self._verify(request):
            return 403, b"<Error><Code>SignatureDoesNotMatch</Code></Error>", \
                "application/xml"
        parts = request.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        with self._lock:
            if request.method == "PUT" and not key:
                self.buckets.add(bucket)
                return 200, b"", "application/xml"
            if not key and request.method in ("GET", "HEAD"):
                return self._list(bucket, request)
            if request.method == "PUT":
                self.buckets.add(bucket)
                self.engine.put(bucket, key, request.body)
                return 200, b"", "application/xml"
            if request.method in ("GET", "HEAD"):
                try:
                    data = self.engine.get(bucket, key)
                except ObjectNotFound:
                    return 404, b"<Error><Code>NoSuchKey</Code></Error>", \
                        "application/xml"
                return 200, (b"" if request.method == "HEAD" else data), \
                    "application/octet-stream"
            if request.method == "DELETE":
                self.engine.delete(bucket, key)
                return 204, b"", "application/xml"
        return 400, b"<Error><Code>BadRequest</Code></Error>", \
            "application/xml"

    def _list(self, bucket: str, request) -> tuple[int, bytes, str]:
        prefix = request.param("prefix")
        max_keys = int(request.param("max-keys") or "1000")
        token = request.param("continuation-token")
        rows = sorted(self.engine.list(bucket, prefix))
        if token:  # opaque token = last key of the previous page
            rows = [r for r in rows if r[0] > token]
        page, rest = rows[:max_keys], rows[max_keys:]
        root = ET.Element("ListBucketResult")
        ET.SubElement(root, "Name").text = bucket
        for key, size, mtime in page:
            item = ET.SubElement(root, "Contents")
            ET.SubElement(item, "Key").text = key
            ET.SubElement(item, "Size").text = str(size)
            ET.SubElement(item, "LastModified").text = \
                _dt.datetime.fromtimestamp(
                    mtime, tz=_dt.timezone.utc).strftime(
                        "%Y-%m-%dT%H:%M:%S.000Z")
        ET.SubElement(root, "IsTruncated").text = \
            "true" if rest else "false"
        if rest and page:
            ET.SubElement(root, "NextContinuationToken").text = page[-1][0]
        return 200, ET.tostring(root), "application/xml"
