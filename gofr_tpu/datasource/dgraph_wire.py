"""Dgraph network client speaking the HTTP API, plus a mini server.

The reference's Dgraph module is a driver-backed network client
(container/datasources.go:408-499 over dgo/gRPC; Dgraph also serves
the same operations over HTTP, which this client speaks):
``POST /mutate?commitNow=true`` with a JSON ``set`` mutation,
``POST /query`` with DQL text, ``POST /alter`` with schema text.
``query(flt, expand)`` *generates* real DQL —
``{ q(func: eq(k, v)) @filter(eq(k2, v2)) { uid expand(_all_) … } }``
— so the bytes on the wire are valid against a real Dgraph alpha. The
method surface mirrors the embedded
:class:`~gofr_tpu.datasource.graph.Dgraph` adapter.

:class:`MiniDgraphServer` serves those endpoints over the embedded
adapter, parsing the DQL subset the client emits.
"""

from __future__ import annotations

import json
import re
from typing import Any

from . import Instrumented
from ._http import json_call
from .graph import Dgraph, GraphEngine, GraphError
from .miniserver import ThreadedHTTPMiniServer


class DgraphWireError(GraphError):
    pass


def _dql_value(value: Any) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return str(value)
    text = str(value).replace("\\", "\\\\").replace('"', '\\"')
    return f'"{text}"'


def build_query_dql(flt: dict, expand: str | None = None) -> str:
    """Filter dict -> one DQL block valid against real Dgraph.

    Predicate names (and the expand edge) ride in the query text, so
    they are validated; values are escaped into DQL literals.
    """
    for name in (*flt, *( [expand] if expand else [] )):
        if not re.fullmatch(r"\w[\w.]*", str(name)):
            raise DgraphWireError(f"invalid predicate name {name!r}")
    items = sorted(flt.items())
    if items:
        k0, v0 = items[0]
        func = f"eq({k0}, {_dql_value(v0)})"
    else:
        func = "has(dgraph.type)"
    filters = " AND ".join(f"eq({k}, {_dql_value(v)})"
                           for k, v in items[1:])
    body = "uid expand(_all_)"
    if expand:
        body += f" {expand} {{ uid expand(_all_) }}"
    dql = f"{{ q(func: {func})"
    if filters:
        dql += f" @filter({filters})"
    return dql + f" {{ {body} }} }}"


class DgraphWire(Instrumented):
    """HTTP client with the embedded adapter's verbs
    (mutate/query/alter)."""

    metric = "app_dgraph_stats"
    log_tag = "DGRAPH"

    def __init__(self, *, endpoint: str = "http://localhost:8080",
                 timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to dgraph", endpoint=self.endpoint)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, path: str, raw: bytes,
              content_type: str) -> tuple[int, Any]:
        return json_call(self.endpoint, "POST", path, raw_body=raw,
                         headers={"Content-Type": content_type},
                         timeout_s=self.timeout_s)

    @staticmethod
    def _check(status: int, data: Any, op: str) -> dict:
        if status != 200 or (isinstance(data, dict) and data.get("errors")):
            raise DgraphWireError(f"{op} -> {status}: {data}")
        return data.get("data", {}) if isinstance(data, dict) else {}

    # ----------------------------------------------------- native verbs
    def mutate(self, set_json: dict | list[dict]) -> dict[str, str]:
        docs = set_json if isinstance(set_json, list) else [set_json]

        def op():
            status, data = self._call(
                "/mutate?commitNow=true",
                json.dumps({"set": docs}).encode(), "application/json")
            return self._check(status, data, "mutate").get("uids", {})
        return self._observed("MUTATE", f"{len(docs)} docs", op)

    def query(self, flt: dict, expand: str | None = None) -> list[dict]:
        def op():
            dql = build_query_dql(flt, expand)
            status, data = self._call("/query", dql.encode(),
                                      "application/dql")
            return self._check(status, data, "query").get("q", [])
        return self._observed("QUERY", str(sorted(flt)), op)

    def alter(self, schema: str) -> None:
        def op():
            status, data = self._call("/alter", schema.encode(),
                                      "application/rdf")
            self._check(status, data, "alter")
        self._observed("ALTER", schema[:40], op)

    def health_check(self) -> dict[str, Any]:
        try:
            status, data = json_call(self.endpoint, "GET", "/health",
                                     timeout_s=self.timeout_s)
            healthy = status == 200
            if isinstance(data, list) and data:
                healthy = healthy and data[0].get("status") == "healthy"
            return {"status": "UP" if healthy else "DOWN",
                    "details": {"endpoint": self.endpoint}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

# quote-aware: a quoted value may contain " AND ", ")" or escaped
# quotes — the value pattern consumes the whole literal before the
# closing paren is matched
_EQ_RE = re.compile(
    r'eq\((\w[\w.]*),\s*(?:"((?:[^"\\]|\\.)*)"|([^)"]+))\)')
_HEAD_RE = re.compile(r"\{\s*q\(func:\s*(eq|has)\(")
_EDGE_RE = re.compile(r"uid expand\(_all_\)\s*(?:(\w+)\s*\{)?")


def _decode_eq(match: "re.Match[str]") -> tuple[str, Any]:
    key, quoted, bare = match.groups()
    if quoted is not None:
        value: Any = quoted.replace('\\"', '"').replace("\\\\", "\\")
    else:
        text = bare.strip()
        if text in ("true", "false"):
            value = text == "true"
        else:
            try:
                value = int(text)
            except ValueError:
                try:
                    value = float(text)
                except ValueError:
                    raise DgraphWireError(
                        f"unsupported DQL value: {text!r}") from None
    return key, value


class MiniDgraphServer(ThreadedHTTPMiniServer):
    """The Dgraph HTTP surface over the embedded adapter, parsing the
    DQL subset :func:`build_query_dql` emits."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(host, port)
        self.store = Dgraph(GraphEngine())

    def handle(self, request) -> tuple[int, bytes, str]:
        try:
            return self._route(request)
        except (GraphError, ValueError) as exc:
            return 200, json.dumps(  # dgraph reports errors in-body
                {"errors": [{"message": str(exc)}]}).encode(), \
                "application/json"

    def _route(self, request) -> tuple[int, bytes, str]:
        path = request.path
        if path == "/health":
            return 200, b'[{"status": "healthy"}]', "application/json"
        if path.startswith("/mutate") and request.method == "POST":
            body = json.loads(request.body)
            uids = self.store.mutate(body.get("set", []))
            return 200, json.dumps(
                {"data": {"uids": uids}}).encode(), "application/json"
        if path == "/query" and request.method == "POST":
            return self._query(request.body.decode())
        if path == "/alter" and request.method == "POST":
            self.store.alter(request.body.decode())
            return 200, b'{"data": {"code": "Success"}}', \
                "application/json"
        return 404, b'{"errors": [{"message": "no route"}]}', \
            "application/json"

    def _query(self, dql: str) -> tuple[int, bytes, str]:
        text = dql.strip()
        head = _HEAD_RE.match(text)
        if not head or "uid expand(_all_)" not in text:
            raise DgraphWireError(f"unsupported DQL: {dql!r}")
        # every eq(...) — func position and @filter conditions alike —
        # contributes one filter entry; the quote-aware regex keeps
        # values containing " AND " or ")" intact
        flt: dict[str, Any] = {}
        for match in _EQ_RE.finditer(text):
            key, value = _decode_eq(match)
            flt[key] = value
        if head.group(1) == "eq" and not flt:
            raise DgraphWireError(f"unsupported DQL predicate in {dql!r}")
        edge = _EDGE_RE.search(text)
        expand = edge.group(1) if edge else None
        rows = self.store.query(flt, expand)
        return 200, json.dumps(
            {"data": {"q": rows}}).encode(), "application/json"
