"""Time-series family: OpenTSDB- and InfluxDB-shaped stores over one
embedded series engine.

Reference interfaces: OpenTSDB container/datasources.go:501-598 (put
datapoints, query with aggregators, annotations), InfluxDB :797-839
(write points to bucket/measurement, query, bucket admin). Adapters
share :class:`SeriesEngine`, an embedded tagged-series store with range
queries and aggregation; production deployments swap the engine for a
network client behind the same interface.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any

from . import Instrumented


class TimeseriesError(Exception):
    pass


_AGGREGATORS = {
    "sum": sum,
    "avg": lambda vs: sum(vs) / len(vs),
    "max": max,
    "min": min,
    "count": len,
    "last": lambda vs: vs[-1],
}


class SeriesEngine:
    """metric + sorted (ts, value, tags) points, range-queryable."""

    def __init__(self) -> None:
        # metric -> sorted list of (ts, value, tags)
        self._series: dict[str, list[tuple[float, float, dict]]] = {}
        self._lock = threading.RLock()

    def put(self, metric: str, ts: float, value: float,
            tags: dict | None = None) -> None:
        with self._lock:
            points = self._series.setdefault(metric, [])
            bisect.insort(points, (float(ts), float(value), tags or {}),
                          key=lambda p: p[0])

    def query(self, metric: str, start: float | None = None,
              end: float | None = None,
              tags: dict | None = None) -> list[tuple[float, float, dict]]:
        with self._lock:
            points = list(self._series.get(metric, []))
        return [p for p in points
                if (start is None or p[0] >= start)
                and (end is None or p[0] <= end)
                and (not tags or all(p[2].get(k) == v
                                     for k, v in tags.items()))]

    def aggregate(self, metric: str, aggregator: str,
                  start: float | None = None, end: float | None = None,
                  tags: dict | None = None) -> float | None:
        if aggregator not in _AGGREGATORS:
            raise TimeseriesError(f"unknown aggregator {aggregator!r}")
        values = [v for _, v, _ in self.query(metric, start, end, tags)]
        return _AGGREGATORS[aggregator](values) if values else None

    def metrics(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"metrics": len(self._series),
                    "points": sum(len(v) for v in self._series.values())}


class _SeriesStore(Instrumented):
    backend_name = "timeseries"

    def __init__(self, engine: SeriesEngine | None = None) -> None:
        self.engine = engine if engine is not None else SeriesEngine()

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.debug(f"connected {self.backend_name} store")

    def health_check(self) -> dict[str, Any]:
        return {"status": "UP", "details": {"backend": self.backend_name,
                                            **self.engine.stats()}}

    def close(self) -> None:
        pass


class OpenTSDB(_SeriesStore):
    """OpenTSDB-shaped surface (reference container/datasources.go:501-598):
    put datapoints, query with aggregator, annotations."""

    metric = "app_opentsdb_stats"
    log_tag = "TSDB"
    backend_name = "opentsdb"

    def __init__(self, engine: SeriesEngine | None = None) -> None:
        super().__init__(engine)
        self._annotations: list[dict] = []

    def put_data_points(self, datapoints: list[dict]) -> int:
        """Each point: {"metric", "timestamp", "value", "tags"?}."""
        def op():
            for p in datapoints:
                self.engine.put(p["metric"], p["timestamp"], p["value"],
                                p.get("tags"))
            return len(datapoints)
        return self._observed("PUT", f"{len(datapoints)} pts", op)

    def query(self, metric: str, aggregator: str = "sum",
              start: float | None = None, end: float | None = None,
              tags: dict | None = None) -> dict:
        def op():
            points = self.engine.query(metric, start, end, tags)
            value = self.engine.aggregate(metric, aggregator, start, end, tags)
            return {"metric": metric, "aggregator": aggregator,
                    "dps": {str(int(ts)): v for ts, v, _ in points},
                    "value": value}
        return self._observed("QUERY", metric, op)

    def put_annotation(self, annotation: dict) -> None:
        self._observed("ANNOTATE", annotation.get("description", "")[:30],
                       lambda: self._annotations.append(dict(annotation)))

    def query_annotations(self, start: float, end: float) -> list[dict]:
        return [a for a in self._annotations
                if start <= a.get("startTime", 0) <= end]


class InfluxDB(_SeriesStore):
    """InfluxDB-shaped surface (reference container/datasources.go:797-839):
    buckets of measurements; write points, query, bucket admin."""

    metric = "app_influxdb_stats"
    log_tag = "INFLUX"
    backend_name = "influxdb"

    def __init__(self, engine: SeriesEngine | None = None) -> None:
        super().__init__(engine)
        self._buckets: set[str] = set()

    @staticmethod
    def _key(bucket: str, measurement: str) -> str:
        return f"{bucket}/{measurement}"

    def create_bucket(self, bucket: str) -> None:
        self._observed("CREATE_BUCKET", bucket,
                       lambda: self._buckets.add(bucket))

    def delete_bucket(self, bucket: str) -> None:
        self._observed("DELETE_BUCKET", bucket,
                       lambda: self._buckets.discard(bucket))

    def list_buckets(self) -> list[str]:
        return sorted(self._buckets)

    def write_point(self, bucket: str, measurement: str, ts: float,
                    fields: dict[str, float],
                    tags: dict | None = None) -> None:
        def op():
            self._buckets.add(bucket)
            for field, value in fields.items():
                self.engine.put(self._key(bucket, measurement), ts, value,
                                dict(tags or {}, _field=field))
        self._observed("WRITE", f"{bucket}/{measurement}", op)

    def query(self, bucket: str, measurement: str, field: str,
              start: float | None = None, end: float | None = None,
              tags: dict | None = None) -> list[tuple[float, float]]:
        def op():
            points = self.engine.query(self._key(bucket, measurement),
                                       start, end,
                                       dict(tags or {}, _field=field))
            return [(ts, v) for ts, v, _ in points]
        return self._observed("QUERY", f"{bucket}/{measurement}", op)

    def aggregate(self, bucket: str, measurement: str, field: str,
                  aggregator: str = "avg", **kw: Any) -> float | None:
        return self.engine.aggregate(self._key(bucket, measurement),
                                     aggregator,
                                     tags={"_field": field}, **kw)

    def health_check(self) -> dict[str, Any]:
        health = super().health_check()
        health["details"]["buckets"] = len(self._buckets)
        return health
