"""Remote file stores: S3-, GCS- and Azure-Blob-shaped filesystems
behind the same ``FileSystem`` interface as the local store.

The reference ships azure/ftp/gcs/s3/sftp modules that all implement
one ``FileSystem`` interface (datasource/interface.go:10-60, modules
datasource/file/{azure,ftp,gcs,s3,sftp}); handlers call ``ctx.file``
the same way regardless of backend. Here each cloud store is an
adapter over :class:`ObjectStoreEngine` — an embedded bucket/key →
bytes engine with object-store semantics (no real directories; key
prefixes emulate them) — exposing BOTH the generic FileSystem surface
(create/read/read_dir/...) and the store's native verbs
(put_object/get_object/list_objects for S3, upload/download blobs for
Azure, ...). A production deployment swaps the engine for a network
client behind the same adapter.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Any

from . import Instrumented
from .file_store import FileError, FileInfo, RowReader


class ObjectNotFound(FileError):
    pass


class ObjectStoreEngine:
    """Embedded bucket/key->bytes store with list-by-prefix."""

    def __init__(self) -> None:
        self._buckets: dict[str, dict[str, tuple[bytes, float]]] = {}
        self._lock = threading.RLock()

    def put(self, bucket: str, key: str, data: bytes) -> None:
        with self._lock:
            self._buckets.setdefault(bucket, {})[key] = (data, time.time())

    def get(self, bucket: str, key: str) -> bytes:
        with self._lock:
            objects = self._buckets.get(bucket, {})
            if key not in objects:
                raise ObjectNotFound(f"{bucket}/{key}")
            return objects[key][0]

    def delete(self, bucket: str, key: str) -> bool:
        with self._lock:
            return self._buckets.get(bucket, {}).pop(key, None) is not None

    def list(self, bucket: str, prefix: str = "") -> list[tuple[str, int, float]]:
        with self._lock:
            objects = self._buckets.get(bucket, {})
            return sorted((k, len(v[0]), v[1]) for k, v in objects.items()
                          if k.startswith(prefix))

    def exists(self, bucket: str, key: str) -> bool:
        with self._lock:
            return key in self._buckets.get(bucket, {})

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"buckets": len(self._buckets),
                    "objects": sum(len(b) for b in self._buckets.values())}


class _ObjectFileSystem(Instrumented):
    """Generic FileSystem surface over one bucket of the engine."""

    backend_name = "object"
    metric = "app_file_stats"
    log_tag = "OBJ"

    def __init__(self, bucket: str,
                 engine: ObjectStoreEngine | None = None) -> None:
        self.bucket = bucket
        self.engine = engine if engine is not None else ObjectStoreEngine()

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.debug(f"connected {self.backend_name} store",
                              bucket=self.bucket)

    @staticmethod
    def _norm(path: str) -> str:
        return path.lstrip("./").lstrip("/")

    # -- FileSystem surface (matches datasource/file_store.py)
    def create(self, path: str, data: bytes | str = b"") -> None:
        payload = data.encode() if isinstance(data, str) else bytes(data)
        self._observed("CREATE", path, lambda: self.engine.put(
            self.bucket, self._norm(path), payload))

    def read(self, path: str) -> bytes:
        return self._observed("READ", path, lambda: self.engine.get(
            self.bucket, self._norm(path)))

    def read_text(self, path: str) -> str:
        return self.read(path).decode()

    def append(self, path: str, data: bytes | str) -> None:
        payload = data.encode() if isinstance(data, str) else bytes(data)
        def op():
            key = self._norm(path)
            try:
                existing = self.engine.get(self.bucket, key)
            except ObjectNotFound:
                existing = b""
            self.engine.put(self.bucket, key, existing + payload)
        self._observed("APPEND", path, op)

    def remove(self, path: str) -> None:
        def op():
            if not self.engine.delete(self.bucket, self._norm(path)):
                raise ObjectNotFound(f"{self.bucket}/{path}")
        self._observed("REMOVE", path, op)

    def rename(self, old: str, new: str) -> None:
        def op():
            data = self.engine.get(self.bucket, self._norm(old))
            self.engine.put(self.bucket, self._norm(new), data)
            self.engine.delete(self.bucket, self._norm(old))
        self._observed("RENAME", f"{old}->{new}", op)

    def stat(self, path: str) -> FileInfo:
        def op():
            key = self._norm(path)
            for k, size, mtime in self.engine.list(self.bucket, key):
                if k == key:
                    return FileInfo(name=key.rsplit("/", 1)[-1], size=size,
                                    mod_time=mtime, is_dir=False)
            raise ObjectNotFound(f"{self.bucket}/{path}")
        return self._observed("STAT", path, op)

    def exists(self, path: str) -> bool:
        return self.engine.exists(self.bucket, self._norm(path))

    def mkdir(self, path: str) -> None:
        # object stores have no directories; prefixes appear on write
        pass

    def remove_all(self, path: str) -> None:
        def op():
            prefix = self._norm(path).rstrip("/")
            for key, _, _ in self.engine.list(self.bucket,
                                              prefix + "/" if prefix else ""):
                self.engine.delete(self.bucket, key)
            self.engine.delete(self.bucket, prefix)
        self._observed("REMOVE_ALL", path, op)

    def read_dir(self, path: str = ".") -> list[FileInfo]:
        def op():
            prefix = self._norm(path if path != "." else "")
            if prefix and not prefix.endswith("/"):
                prefix += "/"
            seen_dirs: set[str] = set()
            out: list[FileInfo] = []
            for key, size, mtime in self.engine.list(self.bucket, prefix):
                rest = key[len(prefix):]
                if "/" in rest:  # emulate one directory level
                    top = rest.split("/", 1)[0]
                    if top not in seen_dirs:
                        seen_dirs.add(top)
                        out.append(FileInfo(name=top, size=0,
                                            mod_time=mtime, is_dir=True))
                else:
                    out.append(FileInfo(name=rest, size=size,
                                        mod_time=mtime, is_dir=False))
            return out
        return self._observed("READ_DIR", path, op)

    def glob(self, pattern: str) -> list[str]:
        return [key for key, _, _ in self.engine.list(self.bucket)
                if fnmatch.fnmatch(key, self._norm(pattern))]

    def read_rows(self, path: str, kind: str | None = None) -> RowReader:
        text = self.read_text(path)
        if kind is None:
            kind = "csv" if path.endswith(".csv") else "json"
        return RowReader(text, kind)

    def health_check(self) -> dict[str, Any]:
        return {"status": "UP",
                "details": {"backend": self.backend_name,
                            "bucket": self.bucket,
                            **self.engine.stats()}}

    def close(self) -> None:
        pass


class S3FileSystem(_ObjectFileSystem):
    """S3-shaped store (reference datasource/file/s3): the FileSystem
    surface plus native object verbs."""

    backend_name = "s3"
    log_tag = "S3"

    def put_object(self, key: str, body: bytes) -> None:
        self.create(key, body)

    def get_object(self, key: str) -> bytes:
        return self.read(key)

    def delete_object(self, key: str) -> None:
        self.remove(key)

    def list_objects(self, prefix: str = "") -> list[dict]:
        return [{"Key": k, "Size": size,
                 "LastModified": mtime}
                for k, size, mtime in self.engine.list(self.bucket, prefix)]


class GCSFileSystem(_ObjectFileSystem):
    """GCS-shaped store (reference datasource/file/gcs)."""

    backend_name = "gcs"
    log_tag = "GCS"

    def upload(self, name: str, data: bytes) -> None:
        self.create(name, data)

    def download(self, name: str) -> bytes:
        return self.read(name)

    def list_blobs(self, prefix: str = "") -> list[str]:
        return [k for k, _, _ in self.engine.list(self.bucket, prefix)]


class AzureBlobFileSystem(_ObjectFileSystem):
    """Azure-Blob-shaped store (reference datasource/file/azure);
    ``bucket`` is the container."""

    backend_name = "azure"
    log_tag = "AZBLOB"

    def upload_blob(self, name: str, data: bytes,
                    overwrite: bool = True) -> None:
        if not overwrite and self.exists(name):
            raise FileError(f"blob exists: {name}")
        self.create(name, data)

    def download_blob(self, name: str) -> bytes:
        return self.read(name)

    def delete_blob(self, name: str) -> None:
        self.remove(name)

    def list_blob_names(self, prefix: str = "") -> list[str]:
        return [k for k, _, _ in self.engine.list(self.bucket, prefix)]
