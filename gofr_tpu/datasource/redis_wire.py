"""Network Redis client speaking RESP2 over TCP, plus a mini server.

The reference connects to a real Redis over the network
(/root/reference/pkg/gofr/datasource/redis/redis.go:43) and hooks every
command for logging/metrics (hook.go:17). :class:`RedisWire` is that
client for this framework: the same command surface as the embedded
:class:`~gofr_tpu.datasource.redis.Redis` (so swapping is the
constructor change redis.py's docstring promises — ``new_redis`` picks
by ``REDIS_MODE``), every call timed into ``app_redis_stats`` through
the shared ProviderMixin hook, RESP2 framing written and parsed from
first principles.

:class:`MiniRedisServer` is miniredis itself (SURVEY §4): a threaded
RESP2 server delegating command semantics to the embedded engine, so
wire-client tests run the real bytes over a real socket with zero
external infrastructure.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any

from . import ProviderMixin
from .redis import Redis, RedisError


class RESP2Error(RedisError):
    pass


# ---------------------------------------------------------------- framing

def encode_command(*args: Any) -> bytes:
    """Client request: RESP2 array of bulk strings."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        elif isinstance(a, bool):
            b = b"1" if a else b"0"
        else:
            b = str(a).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


def encode_reply(value: Any) -> bytes:
    """Server reply encoding for the types the engine returns."""
    if value is None:
        return b"$-1\r\n"
    if isinstance(value, bool):
        return b":%d\r\n" % int(value)
    if isinstance(value, int):
        return b":%d\r\n" % value
    if isinstance(value, float):
        if value == int(value):
            return b":%d\r\n" % int(value)
        b = repr(value).encode()
        return b"$%d\r\n%s\r\n" % (len(b), b)
    if isinstance(value, RedisError):
        return b"-ERR %s\r\n" % str(value).encode()
    if isinstance(value, bytes):
        return b"$%d\r\n%s\r\n" % (len(value), value)
    if isinstance(value, str):
        b = value.encode()
        return b"$%d\r\n%s\r\n" % (len(b), b)
    if isinstance(value, dict):  # HGETALL: flat field/value array
        flat: list[Any] = []
        for k, v in value.items():
            flat.extend((k, v))
        return encode_reply(flat)
    if isinstance(value, (list, tuple, set, frozenset)):
        items = sorted(value, key=str) if isinstance(
            value, (set, frozenset)) else list(value)
        return b"*%d\r\n" % len(items) + b"".join(
            encode_reply(v) for v in items)
    return encode_reply(str(value))


class _SocketReader:
    """Buffered line/exact reads over a blocking socket."""

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.buf = b""

    def read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise RESP2Error("connection closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise RESP2Error("connection closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out


def decode_reply(reader: _SocketReader) -> Any:
    """One RESP2 value: +simple -error :int $bulk *array."""
    line = reader.read_line()
    kind, rest = line[:1], line[1:]
    if kind == b"+":
        return rest.decode()
    if kind == b"-":
        raise RESP2Error(rest.decode())
    if kind == b":":
        return int(rest)
    if kind == b"$":
        n = int(rest)
        if n < 0:
            return None
        data = reader.read_exact(n)
        reader.read_exact(2)  # \r\n
        return data.decode("utf-8", "replace")
    if kind == b"*":
        n = int(rest)
        if n < 0:
            return None
        return [decode_reply(reader) for _ in range(n)]
    raise RESP2Error(f"bad reply type {line[:1]!r}")


# ----------------------------------------------------------------- client

class RedisWire(ProviderMixin):
    """RESP2 network client with the framework Redis command surface.

    Values travel as strings (Redis semantics); numeric replies come
    back as ints. One connection, guarded by a lock — handlers across
    threads share it safely; a dead socket reconnects on next use.
    """

    def __init__(self, *, host: str = "localhost", port: int = 6379,
                 timeout_s: float = 5.0) -> None:
        self.host, self.port = host, port
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._reader: _SocketReader | None = None
        self._lock = threading.RLock()
        self._connected = False

    def connect(self) -> None:
        with self._lock:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._reader = _SocketReader(self._sock)
            self._connected = True
        if self.logger is not None:
            self.logger.info("connected to Redis",
                             addr=f"{self.host}:{self.port}")

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
            self._sock = None
            self._reader = None
            self._connected = False

    def execute(self, *args: Any, _label: str | None = None) -> Any:
        """One command round-trip under the observability hook.
        ``_label`` overrides the metric/log label when the wire command
        differs from the surface method (INCRBY for incr, …)."""
        label = _label or " ".join(str(a) for a in args[:2])

        def op():
            with self._lock:
                if self._sock is None:
                    self.connect()
                assert self._sock is not None and self._reader is not None
                try:
                    self._sock.sendall(encode_command(*args))
                    return decode_reply(self._reader)
                except (OSError, RESP2Error) as exc:
                    if isinstance(exc, RESP2Error) and self._connected \
                            and "connection closed" not in str(exc):
                        raise  # server-side -ERR: connection is fine
                    self.close()
                    raise
        return self._observed(label, op)

    def _observed(self, command: str, fn):
        # identical labels/log shape to the embedded client's hook
        # (redis.py::_observed) so REDIS_MODE swaps don't rename any
        # app_redis_stats series that dashboards key on
        start = time.perf_counter()
        try:
            return fn()
        finally:
            elapsed = time.perf_counter() - start
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_redis_stats", elapsed,
                    type=command.split(" ")[0].lower())
            if self.logger is not None:
                self.logger.debug("REDIS", command=command,
                                  duration_ms=round(elapsed * 1e3, 3))

    # --------------------------------------------------------- commands
    def set(self, key, value, ex: float | None = None) -> bool:
        args = ["SET", key, value] + (["EX", int(ex)] if ex else [])
        return self.execute(*args) == "OK"

    def setex(self, key, seconds, value) -> bool:
        return self.execute("SETEX", key, int(seconds), value) == "OK"

    def get(self, key): return self.execute("GET", key)
    def delete(self, *keys): return self.execute("DEL", *keys)
    def exists(self, *keys): return self.execute("EXISTS", *keys)

    def expire(self, key, seconds) -> bool:
        return bool(self.execute("EXPIRE", key, int(seconds)))

    def ttl(self, key): return self.execute("TTL", key)

    def incr(self, key, by: int = 1):
        return self.execute("INCRBY", key, by, _label=f"INCR {key}")

    def decr(self, key, by: int = 1):
        return self.execute("DECRBY", key, by, _label=f"DECR {key}")

    def hset(self, key, field, value):
        return self.execute("HSET", key, field, value)

    def hget(self, key, field): return self.execute("HGET", key, field)

    def hgetall(self, key) -> dict:
        flat = self.execute("HGETALL", key) or []
        return dict(zip(flat[::2], flat[1::2]))

    def hdel(self, key, *fs): return self.execute("HDEL", key, *fs)
    def lpush(self, key, *vs): return self.execute("LPUSH", key, *vs)
    def rpush(self, key, *vs): return self.execute("RPUSH", key, *vs)

    def lrange(self, key, start, stop) -> list:
        return self.execute("LRANGE", key, start, stop) or []

    def llen(self, key): return self.execute("LLEN", key)
    def lpop(self, key): return self.execute("LPOP", key)
    def rpop(self, key): return self.execute("RPOP", key)
    def sadd(self, key, *ms): return self.execute("SADD", key, *ms)
    def srem(self, key, *ms): return self.execute("SREM", key, *ms)

    def smembers(self, key) -> set:
        return set(self.execute("SMEMBERS", key) or [])

    def sismember(self, key, member) -> bool:
        return bool(self.execute("SISMEMBER", key, member))

    def keys(self, pattern: str = "*") -> list:
        return self.execute("KEYS", pattern) or []

    def flushdb(self) -> bool:
        return self.execute("FLUSHDB") == "OK"

    def ping(self) -> bool:
        return self.execute("PING") in ("PONG", True)

    def health_check(self) -> dict[str, Any]:
        try:
            self.ping()
            return {"status": "UP",
                    "details": {"addr": f"{self.host}:{self.port}",
                                "mode": "network"}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------ mini server

class MiniRedisServer:
    """Threaded RESP2 server over the embedded engine — miniredis."""

    #: command name -> (engine method, encoder of the raw args)
    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.engine = Redis(host="embedded", port=0)
        self.engine.connect()
        self._server: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._running = False

    def start(self) -> None:
        self._server = socket.create_server((self.host, self.port))
        self.port = self._server.getsockname()[1]
        self._running = True
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="mini-redis")
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        assert self._server is not None
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                break
            self._conns.append(conn)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        reader = _SocketReader(conn)
        try:
            while True:
                args = self._read_command(reader)
                if args is None:
                    break
                try:
                    reply = self._execute(args)
                except RedisError as exc:
                    reply = exc
                except Exception as exc:  # malformed args: error, not crash
                    reply = RedisError(str(exc))
                conn.sendall(encode_reply(reply)
                             if not isinstance(reply, _Simple)
                             else b"+%s\r\n" % reply.text.encode())
        except (RESP2Error, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _read_command(self, reader: _SocketReader) -> list[str] | None:
        try:
            line = reader.read_line()
        except RESP2Error:
            return None
        if not line.startswith(b"*"):
            raise RESP2Error(f"expected array, got {line[:1]!r}")
        n = int(line[1:])
        args = []
        for _ in range(n):
            header = reader.read_line()
            size = int(header[1:])
            args.append(reader.read_exact(size).decode())
            reader.read_exact(2)
        return args

    def _execute(self, args: list[str]) -> Any:
        cmd, rest = args[0].upper(), args[1:]
        e = self.engine
        if cmd == "PING":
            return _Simple("PONG")
        if cmd == "SET":
            ex = None
            if len(rest) >= 4 and rest[2].upper() == "EX":
                ex = float(rest[3])
            e.set(rest[0], rest[1], ex=ex)
            return _Simple("OK")
        if cmd == "SETEX":
            e.setex(rest[0], float(rest[1]), rest[2])
            return _Simple("OK")
        if cmd == "FLUSHDB":
            e.flushdb()
            return _Simple("OK")
        if cmd == "INCRBY":
            return e.incr(rest[0], int(rest[1]))
        if cmd == "DECRBY":
            return e.decr(rest[0], int(rest[1]))
        if cmd == "INCR":
            return e.incr(rest[0])
        if cmd == "DECR":
            return e.decr(rest[0])
        if cmd == "EXPIRE":
            return e.expire(rest[0], float(rest[1]))
        if cmd == "TTL":
            return int(e.ttl(rest[0]))
        if cmd == "LRANGE":
            return e.lrange(rest[0], int(rest[1]), int(rest[2]))
        simple = {
            "GET": e.get, "DEL": e.delete, "EXISTS": e.exists,
            "HSET": e.hset, "HGET": e.hget, "HGETALL": e.hgetall,
            "HDEL": e.hdel, "LPUSH": e.lpush, "RPUSH": e.rpush,
            "LLEN": e.llen, "LPOP": e.lpop, "RPOP": e.rpop,
            "SADD": e.sadd, "SREM": e.srem, "SMEMBERS": e.smembers,
            "SISMEMBER": e.sismember, "KEYS": e.keys,
        }.get(cmd)
        if simple is None:
            raise RedisError(f"unknown command '{cmd}'")
        return simple(*rest)

    def close(self) -> None:
        self._running = False
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        for conn in self._conns:  # live connections too, not just the
            try:                  # listener — clients must see the drop
                conn.close()
            except OSError:
                pass
        self._conns.clear()


class _Simple:
    """Marker for RESP2 simple-string replies (+OK vs $2 OK)."""

    def __init__(self, text: str) -> None:
        self.text = text
