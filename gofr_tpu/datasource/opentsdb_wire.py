"""OpenTSDB network client speaking the HTTP API, plus a mini server.

The reference's OpenTSDB module is an HTTP client over the TSDB REST
surface (container/datasources.go:501-598, datasource/opentsdb). This
client speaks that surface directly — ``POST /api/put`` with a JSON
array of datapoints, ``POST /api/query`` with the queries envelope,
``POST /api/annotation`` and ``GET /api/annotation`` — behind the same
method surface as the embedded
:class:`~gofr_tpu.datasource.timeseries.OpenTSDB` adapter, so swapping
is a constructor change.

:class:`MiniOpenTSDBServer` serves those endpoints over the embedded
adapter on the framework's HTTP server.
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Any

from . import Instrumented
from ._http import json_call
from .miniserver import ThreadedHTTPMiniServer
from .timeseries import OpenTSDB, TimeseriesError


class OpenTSDBWireError(TimeseriesError):
    pass


class OpenTSDBWire(Instrumented):
    """HTTP client with the embedded adapter's verbs (put_data_points/
    query/put_annotation/query_annotations)."""

    metric = "app_opentsdb_stats"
    log_tag = "TSDB"

    def __init__(self, *, endpoint: str = "http://localhost:4242",
                 timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to opentsdb",
                             endpoint=self.endpoint)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, method: str, path: str,
              body: Any = None) -> tuple[int, Any]:
        return json_call(self.endpoint, method, path, body=body,
                         timeout_s=self.timeout_s)

    # ----------------------------------------------------- native verbs
    def put_data_points(self, datapoints: list[dict]) -> int:
        def op():
            status, data = self._call("POST", "/api/put?details",
                                      body=datapoints)
            if status not in (200, 204):
                raise OpenTSDBWireError(f"put -> {status}: {data}")
            if isinstance(data, dict) and data.get("failed"):
                raise OpenTSDBWireError(f"put failed points: {data}")
            return len(datapoints)
        return self._observed("PUT", f"{len(datapoints)} pts", op)

    def query(self, metric: str, aggregator: str = "sum",
              start: float | None = None, end: float | None = None,
              tags: dict | None = None) -> dict:
        def op():
            envelope: dict[str, Any] = {
                "queries": [{"metric": metric, "aggregator": aggregator,
                             "tags": tags or {}}]}
            if start is not None:
                envelope["start"] = start
            if end is not None:
                envelope["end"] = end
            status, data = self._call("POST", "/api/query", body=envelope)
            if status != 200:
                raise OpenTSDBWireError(f"query -> {status}: {data}")
            first = data[0] if data else {"metric": metric, "dps": {}}
            return {"metric": first.get("metric", metric),
                    "aggregator": aggregator,
                    "dps": first.get("dps", {}),
                    "value": first.get("value")}
        return self._observed("QUERY", metric, op)

    def put_annotation(self, annotation: dict) -> None:
        def op():
            status, data = self._call("POST", "/api/annotation",
                                      body=annotation)
            if status not in (200, 201, 204):
                raise OpenTSDBWireError(f"annotate -> {status}: {data}")
        self._observed("ANNOTATE",
                       str(annotation.get("description", ""))[:30], op)

    def query_annotations(self, start: float, end: float) -> list[dict]:
        def op():
            params = urllib.parse.urlencode({"start": start, "end": end})
            status, data = self._call("GET", f"/api/annotation?{params}")
            if status != 200:
                raise OpenTSDBWireError(f"annotations -> {status}: {data}")
            return data or []
        return self._observed("ANNOTATIONS", f"{start}-{end}", op)

    def health_check(self) -> dict[str, Any]:
        try:
            status, data = self._call("GET", "/api/version")
            return {"status": "UP" if status == 200 else "DOWN",
                    "details": {"endpoint": self.endpoint,
                                "version": (data or {}).get("version", "")}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

class MiniOpenTSDBServer(ThreadedHTTPMiniServer):
    """The OpenTSDB REST surface over the embedded adapter."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(host, port)
        self.store = OpenTSDB()

    def handle(self, request) -> tuple[int, bytes, str]:
        try:
            return self._route(request)
        except (TimeseriesError, KeyError, ValueError) as exc:
            return 400, json.dumps(
                {"error": {"message": str(exc)}}).encode(), "application/json"

    def _route(self, request) -> tuple[int, bytes, str]:
        path = request.path
        if path == "/api/version":
            return 200, b'{"version": "2.4-mini"}', "application/json"
        if path.startswith("/api/put") and request.method == "POST":
            points = json.loads(request.body)
            if isinstance(points, dict):
                points = [points]
            n = self.store.put_data_points(points)
            return 200, json.dumps(
                {"success": n, "failed": 0}).encode(), "application/json"
        if path == "/api/query" and request.method == "POST":
            envelope = json.loads(request.body)
            out = []
            for q in envelope.get("queries", []):
                result = self.store.query(
                    q["metric"], q.get("aggregator", "sum"),
                    envelope.get("start"), envelope.get("end"),
                    q.get("tags") or None)
                out.append(result)
            return 200, json.dumps(out).encode(), "application/json"
        if path == "/api/annotation" and request.method == "POST":
            self.store.put_annotation(json.loads(request.body))
            return 200, b"{}", "application/json"
        if path.startswith("/api/annotation") and request.method == "GET":
            start = float(request.param("start") or 0)
            end = float(request.param("end") or 2**62)
            found = self.store.query_annotations(start, end)
            return 200, json.dumps(found).encode(), "application/json"
        return 404, b'{"error": {"message": "no route"}}', "application/json"
