"""ClickHouse network client speaking the HTTP interface, plus a mini
server.

The reference's ClickHouse module is a driver-backed network client
(container/datasources.go:196-208 over clickhouse-go). This client
speaks the database's HTTP interface directly — SQL in the POST body,
``FORMAT JSONEachRow`` result streaming, ``?`` placeholders expanded
to escaped literals client-side (the technique the HTTP interface
requires) — behind the same exec/select/async_insert surface as the
embedded :class:`~gofr_tpu.datasource.columnar.Clickhouse` adapter, so
swapping is a constructor change.

:class:`MiniClickhouseServer` serves the HTTP interface over the
embedded adapter on the framework's HTTP server.
"""

from __future__ import annotations

import json
import re
import urllib.parse
import urllib.request
from typing import Any

from . import Instrumented
from .columnar import Clickhouse, ColumnarError
from .miniserver import ThreadedHTTPMiniServer


class ClickhouseWireError(ColumnarError):
    pass


def _literal(value: Any) -> str:
    """Render one bind value as a ClickHouse SQL literal."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, (int, float)):
        return str(value)
    if isinstance(value, bytes):
        value = value.decode("utf-8", "replace")
    text = str(value).replace("\\", "\\\\").replace("'", "\\'")
    return f"'{text}'"


def expand_placeholders(stmt: str, args: tuple) -> str:
    """``?`` -> escaped literals, skipping quoted string literals."""
    out: list[str] = []
    it = iter(args)
    in_string = False
    i = 0
    while i < len(stmt):
        ch = stmt[i]
        if in_string:
            out.append(ch)
            if ch == "\\" and i + 1 < len(stmt):
                out.append(stmt[i + 1])
                i += 1
            elif ch == "'":
                in_string = False
        elif ch == "'":
            in_string = True
            out.append(ch)
        elif ch == "?":
            try:
                out.append(_literal(next(it)))
            except StopIteration:
                raise ClickhouseWireError(
                    "more ? placeholders than arguments") from None
        else:
            out.append(ch)
        i += 1
    leftover = sum(1 for _ in it)
    if leftover:
        raise ClickhouseWireError(f"{leftover} unused bind arguments")
    return "".join(out)


class ClickhouseWire(Instrumented):
    """HTTP-interface client with the embedded adapter's verbs
    (query/select/exec/async_insert)."""

    metric = "app_clickhouse_stats"
    log_tag = "CH"

    def __init__(self, *, endpoint: str = "http://localhost:8123",
                 database: str = "default", username: str = "",
                 password: str = "", timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.database = database
        self.username = username
        self.password = password
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to clickhouse",
                             endpoint=self.endpoint, database=self.database)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, sql: str) -> tuple[int, bytes]:
        params = {"database": self.database}
        url = self.endpoint + "/?" + urllib.parse.urlencode(params)
        headers = {"Content-Type": "text/plain"}
        if self.username:
            headers["X-ClickHouse-User"] = self.username
            headers["X-ClickHouse-Key"] = self.password
        req = urllib.request.Request(url, data=sql.encode(), method="POST",
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read()

    # ----------------------------------------------------- native verbs
    def query(self, stmt: str, *args: Any) -> list[dict]:
        def op():
            sql = expand_placeholders(stmt, args)
            # only a real trailing FORMAT clause counts — 'format' in an
            # identifier or literal must not suppress JSONEachRow
            if not re.search(r"\bformat\s+\w+\s*$", sql,
                             re.IGNORECASE):
                sql += " FORMAT JSONEachRow"
            status, data = self._call(sql)
            if status != 200:
                raise ClickhouseWireError(
                    f"query -> {status}: {data[:200].decode('utf-8', 'replace')}")
            return [json.loads(line) for line in data.splitlines() if line]
        return self._observed("QUERY", stmt.split(None, 1)[0], op)

    def select(self, stmt: str, *args: Any) -> list[dict]:
        return self.query(stmt, *args)

    def exec(self, stmt: str, *args: Any) -> None:
        def op():
            status, data = self._call(expand_placeholders(stmt, args))
            if status != 200:
                raise ClickhouseWireError(
                    f"exec -> {status}: {data[:200].decode('utf-8', 'replace')}")
        self._observed("EXEC", stmt.split(None, 1)[0], op)

    def async_insert(self, stmt: str, *args: Any) -> None:
        # the HTTP interface point is fire-and-forget; exec satisfies it
        self.exec(stmt, *args)

    def health_check(self) -> dict[str, Any]:
        try:
            status, data = self._call("SELECT 1")
            return {"status": "UP" if status == 200 else "DOWN",
                    "details": {"endpoint": self.endpoint,
                                "database": self.database}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

_FORMAT_SUFFIX = " FORMAT JSONEACHROW"


def _ch_to_sqlite(sql: str) -> str:
    """Translate ClickHouse string-literal escapes (backslash style)
    into sqlite's doubled-quote style, so the mini server lexes
    literals the way real ClickHouse does."""
    out: list[str] = []
    in_string = False
    i = 0
    while i < len(sql):
        ch = sql[i]
        if not in_string:
            out.append(ch)
            if ch == "'":
                in_string = True
        elif ch == "\\" and i + 1 < len(sql):
            nxt = sql[i + 1]
            out.append("''" if nxt == "'" else nxt)
            i += 1
        elif ch == "'":
            in_string = False
            out.append(ch)
        else:
            out.append(ch)
        i += 1
    return "".join(out)


class MiniClickhouseServer(ThreadedHTTPMiniServer):
    """The ClickHouse HTTP interface over the embedded adapter."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(host, port)
        self.store = Clickhouse()
        self.store.connect()

    def handle(self, request) -> tuple[int, bytes, str]:
        sql = (request.body or b"").decode().strip()
        if not sql:
            sql = request.param("query") or ""
        if not sql:
            return 400, b"no query", "text/plain"
        wants_json = sql.upper().endswith(_FORMAT_SUFFIX)
        if wants_json:
            sql = sql[:-len(_FORMAT_SUFFIX)].rstrip()
        sql = _ch_to_sqlite(sql)
        try:
            word = sql.split(None, 1)[0].upper() if sql.split() else ""
            if word in ("SELECT", "WITH", "SHOW"):
                rows = self.store.query(sql)
                body = "\n".join(json.dumps(r) for r in rows)
                return 200, body.encode(), "application/x-ndjson"
            self.store.exec(sql)
            return 200, b"", "text/plain"
        except Exception as exc:
            return 400, f"Code: 62. DB::Exception: {exc}".encode(), \
                "text/plain"
