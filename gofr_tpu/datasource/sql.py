"""Instrumented SQL datasource.

The analog of reference ``datasource/sql`` (sql.go:74, db.go:20): a
dialect-aware connection whose every ``query``/``exec`` emits a
structured ``QueryLog`` and an ``app_sql_stats`` histogram sample
(db.go:47-60), plus an ORM-lite ``select`` that maps rows into
dataclasses (db.go:214) and a transaction wrapper (db.go:124).

Backends: sqlite (stdlib, always available), network postgres-family
servers via :class:`~gofr_tpu.datasource.postgres_wire.PostgresWire`
(the v3 wire protocol, ``DB_DIALECT=postgres`` + ``DB_HOST``), and
network mysql servers via
:class:`~gofr_tpu.datasource.mysql_wire.MySQLWire` (the client/server
protocol, ``DB_DIALECT=mysql`` + ``DB_HOST``). All dialects share the
query builder and auto-CRUD (placeholder style, AUTOINCREMENT
spelling).
"""

from __future__ import annotations

import contextvars
import re
import sqlite3
import threading
import time
from dataclasses import dataclass, fields, is_dataclass
from typing import Any, Iterator, Sequence

from contextlib import contextmanager

from . import ProviderMixin

# identifies the task/thread context that owns an open transaction, so
# interleaved async handlers on one event-loop thread can't stomp it
_CURRENT_TX: contextvars.ContextVar[object | None] = \
    contextvars.ContextVar("gofr_sql_tx", default=None)

DIALECT_SQLITE = "sqlite"
DIALECT_MYSQL = "mysql"
DIALECT_POSTGRES = "postgres"
DIALECT_COCKROACH = "cockroachdb"
DIALECT_SUPABASE = "supabase"
DIALECT_ORACLE = "oracle"  # network wire client only (oracle_wire)

_DIALECTS = (DIALECT_SQLITE, DIALECT_MYSQL, DIALECT_POSTGRES,
             DIALECT_COCKROACH, DIALECT_SUPABASE)

# dialects whose driver placeholder is $N (postgres family)
_DOLLAR_PLACEHOLDER = (DIALECT_POSTGRES, DIALECT_COCKROACH, DIALECT_SUPABASE)


class SQLError(Exception):
    pass


@dataclass
class QueryLog:
    """One executed statement (reference sql/db.go QueryLog)."""

    query: str
    duration_us: int
    args: tuple = ()

    def pretty_print(self) -> str:
        return f"SQL {self.duration_us:8d}µs {self.query}"


def placeholder(dialect: str, n: int) -> str:
    """The n-th (1-based) bind placeholder for a dialect
    (reference sql/query_builder.go)."""
    if dialect in _DOLLAR_PLACEHOLDER:
        return f"${n}"
    if dialect == DIALECT_ORACLE:
        return f":{n}"
    return "?"


def placeholders(dialect: str, count: int) -> str:
    return ", ".join(placeholder(dialect, i + 1) for i in range(count))


_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def quote_ident(name: str) -> str:
    """Validate-and-quote an identifier destined for SQL text.

    Auto-CRUD builds statements from dataclass field names; this is the
    single gate that keeps those from becoming injection vectors.
    """
    if not _IDENT_RE.match(name):
        raise SQLError(f"invalid SQL identifier: {name!r}")
    return name


class Tx:
    """Transaction handle (reference sql/db.go:124)."""

    def __init__(self, db: "SQL") -> None:
        self._db = db

    def query(self, query: str, *args: Any) -> list[sqlite3.Row]:
        return self._db.query(query, *args)

    def query_row(self, query: str, *args: Any) -> sqlite3.Row | None:
        return self._db.query_row(query, *args)

    def exec(self, query: str, *args: Any) -> sqlite3.Cursor:
        # no per-statement commit: begin() commits/rolls back the batch
        return self._db._execute(query, args, commit=False)

    def ph(self, n: int) -> str:
        return self._db.ph(n)

    def select(self, entity_type: type, query: str, *args: Any) -> list[Any]:
        return self._db.select(entity_type, query, *args)


class SQL(ProviderMixin):
    """Connection + instrumentation (reference sql/db.go:20)."""

    def __init__(self, *, dialect: str = DIALECT_SQLITE,
                 database: str = ":memory:") -> None:
        if dialect not in _DIALECTS:
            raise SQLError(f"unsupported dialect {dialect!r}; "
                           f"one of {_DIALECTS}")
        self.dialect = dialect
        self.database = database
        self._conn: sqlite3.Connection | None = None
        # sqlite connections are not thread-safe; handlers run on a
        # thread pool, so serialize at the wrapper
        self._lock = threading.RLock()
        self._tx_token: object | None = None

    def connect(self) -> None:
        if self.dialect != DIALECT_SQLITE:
            raise SQLError(
                f"no driver for dialect {self.dialect!r} in this build; "
                "sqlite is the shipped backend")
        # isolation_level=None -> true autocommit; begin() issues an
        # explicit BEGIN so DDL rides the transaction too (sqlite's
        # legacy implicit-BEGIN mode auto-commits DDL, which would make
        # "transactional migrations" silently non-transactional)
        self._conn = sqlite3.connect(self.database,
                                     check_same_thread=False,
                                     isolation_level=None)
        self._conn.row_factory = sqlite3.Row
        if self.logger is not None:
            self.logger.info("connected to SQL",
                             dialect=self.dialect, database=self.database)

    # ----------------------------------------------------- instrumented
    def _observe(self, query: str, args: tuple, start: float) -> None:
        duration_us = int((time.perf_counter() - start) * 1e6)
        if self.logger is not None:
            self.logger.debug(QueryLog(query, duration_us, args).pretty_print())
        if self.metrics is not None:
            self.metrics.record_histogram("app_sql_stats", duration_us / 1e6,
                                          type=query.split(None, 1)[0].lower()
                                          if query.split() else "unknown")

    def ph(self, n: int) -> str:
        """The n-th (1-based) bind placeholder for this dialect."""
        return placeholder(self.dialect, n)

    def _require_conn(self) -> sqlite3.Connection:
        if self._conn is None:
            raise SQLError("SQL not connected; call connect() first")
        return self._conn

    def _guard_tx(self) -> None:
        """Call with the lock held. The RLock is thread-keyed, so it
        can't protect an open transaction from other asyncio tasks
        interleaving on the same loop thread; the context-var token
        closes that hole. Cross-thread callers never see it — they
        block on the lock until the transaction releases it."""
        if (self._tx_token is not None
                and _CURRENT_TX.get() is not self._tx_token):
            raise SQLError(
                "a transaction is open on this connection from another "
                "task; run this statement inside that begin() block or "
                "after it commits")

    def query(self, query: str, *args: Any) -> list[sqlite3.Row]:
        conn = self._require_conn()
        start = time.perf_counter()
        span = self.tracer.start_span(f"sql {query.split(None, 1)[0]}") \
            if self.tracer is not None else None
        try:
            with self._lock:
                self._guard_tx()
                cur = conn.execute(query, args)
                return cur.fetchall()
        finally:
            if span is not None:
                span.end()
            self._observe(query, args, start)

    def query_row(self, query: str, *args: Any) -> sqlite3.Row | None:
        rows = self.query(query, *args)
        return rows[0] if rows else None

    def exec(self, query: str, *args: Any) -> sqlite3.Cursor:
        return self._execute(query, args, commit=True)

    def _execute(self, query: str, args: tuple,
                 commit: bool) -> sqlite3.Cursor:
        conn = self._require_conn()
        start = time.perf_counter()
        try:
            with self._lock:
                if commit:
                    self._guard_tx()
                cur = conn.execute(query, args)
                if commit:
                    conn.commit()
                return cur
        finally:
            self._observe(query, args, start)

    @contextmanager
    def begin(self) -> Iterator[Tx]:
        """Transaction with commit-on-success / rollback-on-raise
        (reference sql/db.go:124, migration/migration.go:68-97)."""
        conn = self._require_conn()
        with self._lock:
            token = object()
            self._tx_token = token
            ctx_token = _CURRENT_TX.set(token)
            conn.execute("BEGIN IMMEDIATE")
            try:
                yield Tx(self)
                conn.execute("COMMIT")
            except BaseException:
                conn.execute("ROLLBACK")
                raise
            finally:
                self._tx_token = None
                _CURRENT_TX.reset(ctx_token)

    # ---------------------------------------------------------- ORM-lite
    def select(self, entity_type: type, query: str, *args: Any) -> list[Any]:
        """Map rows into dataclass instances by field name
        (reference sql/db.go:214 reflection Select)."""
        if not is_dataclass(entity_type):
            raise SQLError("select requires a dataclass type")
        names = [f.name for f in fields(entity_type)]
        out = []
        for row in self.query(query, *args):
            keys = set(row.keys())
            out.append(entity_type(**{n: row[n] for n in names if n in keys}))
        return out

    # ------------------------------------------------------------ health
    def health_check(self) -> dict[str, Any]:
        try:
            self._require_conn().execute("SELECT 1")
            return {"status": "UP", "details": {"dialect": self.dialect,
                                                "database": self.database}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None


def new_sql(config: Any, logger: Any = None, metrics: Any = None,
            tracer: Any = None) -> SQL | None:
    """Env-driven constructor (reference sql/sql.go:74): returns None
    when DB_DIALECT is unset. A configured-but-unconnectable database
    logs and degrades instead of failing the whole boot, matching the
    reference's log-and-retry connect loop."""
    dialect = config.get("DB_DIALECT") if config else None
    if not dialect:
        return None
    host = config.get("DB_HOST")
    if dialect == DIALECT_ORACLE and not host:
        # the embedded engine has no oracle mode — surface the actual
        # misconfiguration, not an "unsupported dialect" red herring
        if logger is not None:
            logger.error("SQL disabled: DB_DIALECT=oracle requires "
                         "DB_HOST (the TNS wire client)")
        return None
    if host and (dialect in _DOLLAR_PLACEHOLDER
                 or dialect in (DIALECT_MYSQL, DIALECT_ORACLE)):
        # a network server: dial it over the real wire protocol
        # (reference sql.go:74 does this via lib/pq / go-sql-driver;
        # oracle rides its own wire module, TNS + O5LOGON)
        default_port = {DIALECT_MYSQL: "3306",
                        DIALECT_ORACLE: "1521"}.get(dialect, "5432")
        try:
            port = int(config.get_or_default("DB_PORT",
                                             default_port).strip())
        except ValueError:
            if logger is not None:
                logger.error("SQL disabled: DB_PORT is not an integer")
            return None
        user = config.get_or_default(
            "DB_USER", {DIALECT_MYSQL: "root",
                        DIALECT_ORACLE: "system"}.get(dialect, "postgres"))
        password = config.get_or_default("DB_PASSWORD", "")
        name = config.get_or_default(
            "DB_NAME", {DIALECT_MYSQL: "",
                        DIALECT_ORACLE: "FREEPDB1"}.get(dialect,
                                                        "postgres"))
        if dialect == DIALECT_MYSQL:
            from .mysql_wire import MySQLWire
            db: Any = MySQLWire(host=host, port=port, user=user,
                                password=password, database=name)
        elif dialect == DIALECT_ORACLE:
            from .oracle_wire import OracleWire
            db = OracleWire(host=host, port=port, username=user,
                            password=password, service_name=name)
        else:
            from .postgres_wire import PostgresWire
            db = PostgresWire(host=host, port=port, user=user,
                              password=password, database=name)
        for use, obj in (("use_logger", logger), ("use_metrics", metrics),
                         ("use_tracer", tracer)):
            if obj is not None:
                getattr(db, use)(obj)
        try:
            db.connect()
        except Exception as exc:
            if logger is not None:
                logger.error(f"SQL connect failed: {exc}")
            return None
        return db
    try:
        db = SQL(dialect=dialect,
                 database=config.get_or_default("DB_NAME", ":memory:"))
    except SQLError as exc:
        if logger is not None:
            logger.error(f"SQL disabled: {exc}")
        return None
    if logger is not None:
        db.use_logger(logger)
    if metrics is not None:
        db.use_metrics(metrics)
    if tracer is not None:
        db.use_tracer(tracer)
    try:
        db.connect()
    except SQLError as exc:
        if logger is not None:
            logger.error(f"SQL connect failed: {exc}")
        return None
    return db


def scan_rows(rows: Sequence[sqlite3.Row]) -> list[dict[str, Any]]:
    """Rows → list of dicts (JSON-ready)."""
    return [dict(r) for r in rows]
