"""Cassandra network client speaking the CQL native protocol v4, plus
a mini server.

The reference's Cassandra module is a driver-backed network client
(container/datasources.go:42-188 over gocql). This client implements
the native protocol itself over a TCP socket: the 9-byte frame header
(version/flags/stream/opcode/length), STARTUP → READY/AUTHENTICATE,
PlainText SASL auth (AUTH_RESPONSE ``\\0user\\0password`` →
AUTH_SUCCESS), QUERY and BATCH opcodes, and RESULT parsing (Void and
Rows kinds with typed column decode: bigint/double/boolean/varchar/
blob). Bind arguments are rendered as CQL literals client-side, which
keeps the frames valid against real Cassandra.

The method surface mirrors the embedded
:class:`~gofr_tpu.datasource.columnar.Cassandra` adapter (query/exec/
new_batch/batch_query/execute_batch/health_check), so swapping is a
constructor change.

:class:`MiniCassandraServer` implements the server half of the same
frames over the embedded adapter — hermetic wire tests, real bytes,
verified auth.
"""

from __future__ import annotations

import re
import socket
import socketserver
import struct
import threading
from typing import Any

from . import Instrumented
from .columnar import Cassandra, ColumnarError

REQUEST_VERSION = 0x04
RESPONSE_VERSION = 0x84

OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_OPTIONS = 0x05
OP_SUPPORTED = 0x06
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_BATCH = 0x0D
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

RESULT_VOID = 0x0001
RESULT_ROWS = 0x0002

TYPE_BIGINT = 0x0002
TYPE_BLOB = 0x0003
TYPE_BOOLEAN = 0x0004
TYPE_DOUBLE = 0x0007
TYPE_VARCHAR = 0x000D

CONSISTENCY_ONE = 0x0001


class CassandraWireError(ColumnarError):
    """Server ERROR frame, with the protocol error code."""

    def __init__(self, message: str, code: int = 0) -> None:
        super().__init__(message)
        self.code = code


# ----------------------------------------------------------- primitives

def _string(s: str) -> bytes:
    data = s.encode()
    return struct.pack("!H", len(data)) + data


def _long_string(s: str) -> bytes:
    data = s.encode()
    return struct.pack("!I", len(data)) + data


def _string_map(m: dict[str, str]) -> bytes:
    out = [struct.pack("!H", len(m))]
    for k, v in m.items():
        out.append(_string(k))
        out.append(_string(v))
    return b"".join(out)


def _read_string(body: bytes, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from("!H", body, off)
    off += 2
    return body[off:off + n].decode(), off + n


def _read_long_string(body: bytes, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from("!I", body, off)
    off += 4
    return body[off:off + n].decode(), off + n


def cql_literal(value: Any) -> str:
    """Render one bind value as a CQL literal."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return str(value)
    if isinstance(value, bytes):
        return "0x" + value.hex()
    return "'" + str(value).replace("'", "''") + "'"


def expand_qmarks(stmt: str, args: tuple) -> str:
    """``?`` bind markers -> CQL literals, skipping quoted literals."""
    out: list[str] = []
    it = iter(args)
    in_string = False
    i = 0
    while i < len(stmt):
        ch = stmt[i]
        if in_string:
            out.append(ch)
            if ch == "'":
                # '' is an escaped quote inside the literal
                if i + 1 < len(stmt) and stmt[i + 1] == "'":
                    out.append("'")
                    i += 1
                else:
                    in_string = False
        elif ch == "'":
            in_string = True
            out.append(ch)
        elif ch == "?":
            try:
                out.append(cql_literal(next(it)))
            except StopIteration:
                raise CassandraWireError(
                    "more ? markers than arguments") from None
        else:
            out.append(ch)
        i += 1
    leftover = sum(1 for _ in it)
    if leftover:
        raise CassandraWireError(f"{leftover} unused bind arguments")
    return "".join(out)


class _FrameSocket:
    """Framed read/write over a blocking socket."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._buf = b""

    def _exactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise CassandraWireError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def send(self, version: int, opcode: int, body: bytes,
             stream: int = 0) -> None:
        header = struct.pack("!BBhBI", version, 0, stream, opcode, len(body))
        self._sock.sendall(header + body)

    def recv(self) -> tuple[int, int, bytes]:
        """-> (opcode, stream, body)."""
        header = self._exactly(9)
        _version, _flags, stream, opcode, length = struct.unpack(
            "!BBhBI", header)
        return opcode, stream, self._exactly(length)


# ---------------------------------------------------------------- client

class CassandraWire(Instrumented):
    """CQL native-protocol client with the embedded adapter's verbs."""

    metric = "app_cassandra_stats"
    log_tag = "CQL"

    def __init__(self, *, host: str = "localhost", port: int = 9042,
                 keyspace: str = "default", username: str = "",
                 password: str = "", timeout_s: float = 30.0) -> None:
        self.host = host
        self.port = port
        self.keyspace = keyspace
        self.username = username
        self.password = password
        self.timeout_s = timeout_s
        self._frames: _FrameSocket | None = None
        self._sock: socket.socket | None = None
        self._lock = threading.RLock()
        self._batches: dict[str, list[str]] = {}

    def connect(self) -> None:
        if self._sock is not None:
            self.close()
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._frames = _FrameSocket(sock)
        try:
            self._frames.send(REQUEST_VERSION, OP_STARTUP,
                              _string_map({"CQL_VERSION": "3.0.0"}))
            opcode, _, body = self._frames.recv()
            if opcode == OP_AUTHENTICATE:
                token = b"\x00" + self.username.encode() \
                    + b"\x00" + self.password.encode()
                self._frames.send(
                    REQUEST_VERSION, OP_AUTH_RESPONSE,
                    struct.pack("!i", len(token)) + token)
                opcode, _, body = self._frames.recv()
                if opcode != OP_AUTH_SUCCESS:
                    raise self._as_error(opcode, body)
            elif opcode != OP_READY:
                raise self._as_error(opcode, body)
        except BaseException:
            sock.close()
            self._sock = None
            self._frames = None
            raise
        if self.logger is not None:
            self.logger.info("connected to cassandra", host=self.host,
                             port=self.port, keyspace=self.keyspace)

    @staticmethod
    def _as_error(opcode: int, body: bytes) -> CassandraWireError:
        if opcode == OP_ERROR:
            (code,) = struct.unpack_from("!I", body, 0)
            message, _ = _read_string(body, 4)
            return CassandraWireError(message, code=code)
        return CassandraWireError(f"unexpected opcode {opcode:#x}")

    def _require(self) -> _FrameSocket:
        if self._frames is None:
            raise CassandraWireError("not connected; call connect() first")
        return self._frames

    def _round_trip(self, opcode: int, body: bytes) -> tuple[int, bytes]:
        frames = self._require()
        with self._lock:
            try:
                frames.send(REQUEST_VERSION, opcode, body)
                got, _, payload = frames.recv()
            except (OSError, TimeoutError) as exc:
                # a partial frame poisons the stream — the next recv
                # would pair with THIS request's late response
                self.close()
                raise CassandraWireError(
                    f"connection lost mid-request ({exc}); "
                    "reconnect required") from exc
        if got == OP_ERROR:
            raise self._as_error(got, payload)
        return got, payload

    def _run(self, cql: str) -> list[dict]:
        body = _long_string(cql) + struct.pack("!HB", CONSISTENCY_ONE, 0)
        opcode, payload = self._round_trip(OP_QUERY, body)
        if opcode != OP_RESULT:
            raise CassandraWireError(f"unexpected opcode {opcode:#x}")
        return _parse_result(payload)

    # ----------------------------------------------------- native verbs
    def query(self, stmt: str, *args: Any) -> list[dict]:
        return self._observed(
            "QUERY", stmt.split(None, 1)[0],
            lambda: self._run(expand_qmarks(stmt, args)))

    def exec(self, stmt: str, *args: Any) -> None:
        self._observed("EXEC", stmt.split(None, 1)[0],
                       lambda: self._run(expand_qmarks(stmt, args)))

    query_with_ctx = query
    exec_with_ctx = exec

    # -- batches (protocol BATCH opcode, one frame for the whole set)
    def new_batch(self, name: str, _batch_type: int = 0) -> None:
        with self._lock:
            self._batches[name] = []

    def batch_query(self, name: str, stmt: str, *args: Any) -> None:
        with self._lock:
            if name not in self._batches:
                raise ColumnarError(f"batch {name!r} not initialised")
            self._batches[name].append(expand_qmarks(stmt, args))

    def execute_batch(self, name: str) -> None:
        def op():
            with self._lock:
                if name not in self._batches:
                    raise ColumnarError(f"batch {name!r} not initialised")
                stmts = self._batches.pop(name)
            parts = [struct.pack("!BH", 0, len(stmts))]  # logged batch
            for cql in stmts:
                parts.append(b"\x00")  # kind 0: query string
                parts.append(_long_string(cql))
                parts.append(struct.pack("!H", 0))  # no values
            parts.append(struct.pack("!HB", CONSISTENCY_ONE, 0))
            opcode, payload = self._round_trip(OP_BATCH, b"".join(parts))
            if opcode != OP_RESULT:
                raise CassandraWireError(f"unexpected opcode {opcode:#x}")
        self._observed("BATCH", name, op)

    def health_check(self) -> dict[str, Any]:
        try:
            self._run("SELECT 1")
            return {"status": "UP",
                    "details": {"host": self.host, "port": self.port,
                                "keyspace": self.keyspace}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None
            self._frames = None


class ScyllaWire(CassandraWire):
    """ScyllaDB speaks the same CQL native protocol (reference
    container/datasources.go:600-635 keeps a separate surface; only
    the metrics identity differs here)."""

    metric = "app_scylladb_stats"
    log_tag = "SCYLLA"


def _parse_result(payload: bytes) -> list[dict]:
    (kind,) = struct.unpack_from("!I", payload, 0)
    if kind != RESULT_ROWS:
        return []
    off = 4
    (flags,) = struct.unpack_from("!I", payload, off)
    off += 4
    (col_count,) = struct.unpack_from("!I", payload, off)
    off += 4
    global_spec = bool(flags & 0x0001)
    if global_spec:
        _, off = _read_string(payload, off)  # keyspace
        _, off = _read_string(payload, off)  # table
    columns: list[tuple[str, int]] = []
    for _ in range(col_count):
        if not global_spec:
            _, off = _read_string(payload, off)
            _, off = _read_string(payload, off)
        name, off = _read_string(payload, off)
        (type_id,) = struct.unpack_from("!H", payload, off)
        off += 2
        columns.append((name, type_id))
    (row_count,) = struct.unpack_from("!I", payload, off)
    off += 4
    rows = []
    for _ in range(row_count):
        row: dict[str, Any] = {}
        for name, type_id in columns:
            (length,) = struct.unpack_from("!i", payload, off)
            off += 4
            if length == -1:
                row[name] = None
            else:
                row[name] = _decode_value(payload[off:off + length], type_id)
                off += length
        rows.append(row)
    return rows


def _decode_value(data: bytes, type_id: int) -> Any:
    if type_id == TYPE_BIGINT:
        return struct.unpack("!q", data)[0]
    if type_id == TYPE_DOUBLE:
        return struct.unpack("!d", data)[0]
    if type_id == TYPE_BOOLEAN:
        return data != b"\x00"
    if type_id == TYPE_BLOB:
        return data
    return data.decode()


def _encode_value(value: Any) -> tuple[int, bytes]:
    """-> (type_id, encoded bytes) for one column value."""
    if isinstance(value, bool):
        return TYPE_BOOLEAN, (b"\x01" if value else b"\x00")
    if isinstance(value, int):
        return TYPE_BIGINT, struct.pack("!q", value)
    if isinstance(value, float):
        return TYPE_DOUBLE, struct.pack("!d", value)
    if isinstance(value, bytes):
        return TYPE_BLOB, value
    return TYPE_VARCHAR, str(value).encode()


# ------------------------------------------------------------ mini server

# CQL spells blobs 0xBEEF; sqlite spells them X'BEEF' — translate
# outside string literals only
_CQL_BLOB_RE = re.compile(r"'(?:[^']|'')*'|\b0x([0-9a-fA-F]+)\b")


def _cql_to_sqlite(cql: str) -> str:
    def sub(match: "re.Match[str]") -> str:
        if match.group(1) is None:  # a quoted literal
            return match.group(0)
        return f"X'{match.group(1)}'"
    return _CQL_BLOB_RE.sub(sub, cql)


class _CQLHandler(socketserver.BaseRequestHandler):
    @property
    def mini(self) -> "MiniCassandraServer":
        return self.server.mini  # type: ignore[attr-defined]

    def handle(self) -> None:
        frames = _FrameSocket(self.request)
        try:
            if not self._startup(frames):
                return
            while True:
                opcode, stream, body = frames.recv()
                if opcode == OP_OPTIONS:
                    frames.send(RESPONSE_VERSION, OP_SUPPORTED,
                                _string_map({}), stream)
                elif opcode == OP_QUERY:
                    cql, off = _read_long_string(body, 0)
                    self._run_and_reply(frames, stream, [cql])
                elif opcode == OP_BATCH:
                    off = 1  # batch type
                    (n,) = struct.unpack_from("!H", body, off)
                    off += 2
                    stmts = []
                    for _ in range(n):
                        off += 1  # kind byte (0: query string)
                        cql, off = _read_long_string(body, off)
                        (nvals,) = struct.unpack_from("!H", body, off)
                        off += 2  # no values supported in batches
                        stmts.append(cql)
                    self._run_and_reply(frames, stream, stmts,
                                        batch=True)
                else:
                    self._error(frames, stream, 0x000A,
                                f"unsupported opcode {opcode:#x}")
        except (CassandraWireError, ConnectionError, OSError):
            return

    def _startup(self, frames: _FrameSocket) -> bool:
        opcode, stream, _body = frames.recv()
        if opcode == OP_OPTIONS:  # driver probing before startup
            frames.send(RESPONSE_VERSION, OP_SUPPORTED, _string_map({}),
                        stream)
            opcode, stream, _body = frames.recv()
        if opcode != OP_STARTUP:
            return False
        if not self.mini.password:
            frames.send(RESPONSE_VERSION, OP_READY, b"", stream)
            return True
        frames.send(
            RESPONSE_VERSION, OP_AUTHENTICATE,
            _string("org.apache.cassandra.auth.PasswordAuthenticator"),
            stream)
        opcode, stream, body = frames.recv()
        if opcode != OP_AUTH_RESPONSE:
            return False
        (n,) = struct.unpack_from("!i", body, 0)
        token = body[4:4 + n] if n > 0 else b""
        parts = token.split(b"\x00")
        ok = (len(parts) == 3
              and parts[1].decode() == self.mini.user
              and parts[2].decode() == self.mini.password)
        if not ok:
            self._error(frames, stream, 0x0100, "bad credentials")
            return False
        frames.send(RESPONSE_VERSION, OP_AUTH_SUCCESS,
                    struct.pack("!i", -1), stream)
        return True

    def _error(self, frames: _FrameSocket, stream: int, code: int,
               message: str) -> None:
        frames.send(RESPONSE_VERSION, OP_ERROR,
                    struct.pack("!I", code) + _string(message), stream)

    def _run_and_reply(self, frames: _FrameSocket, stream: int,
                       stmts: list[str], batch: bool = False) -> None:
        try:
            rows: list[dict] = []
            stmts = [_cql_to_sqlite(s) for s in stmts]
            if batch:
                name = f"_wire_{id(stmts):x}"
                self.mini.store.new_batch(name)
                for cql in stmts:
                    self.mini.store.batch_query(name, cql)
                self.mini.store.execute_batch(name)
            else:
                word = stmts[0].split(None, 1)[0].upper() \
                    if stmts[0].split() else ""
                if word == "SELECT":
                    rows = self.mini.store.query(stmts[0])
                else:
                    self.mini.store.exec(stmts[0])
                    frames.send(RESPONSE_VERSION, OP_RESULT,
                                struct.pack("!I", RESULT_VOID), stream)
                    return
        except Exception as exc:
            self._error(frames, stream, 0x2000, str(exc))
            return
        if batch:
            frames.send(RESPONSE_VERSION, OP_RESULT,
                        struct.pack("!I", RESULT_VOID), stream)
            return
        frames.send(RESPONSE_VERSION, OP_RESULT,
                    _encode_rows(rows, self.mini.keyspace), stream)


def _encode_rows(rows: list[dict], keyspace: str) -> bytes:
    columns = list(rows[0].keys()) if rows else []
    # a column's wire type must hold for EVERY value in it — sqlite
    # allows mixed types, so columns that mix degrade to varchar
    types = []
    for name in columns:
        seen = {_encode_value(r[name])[0] for r in rows
                if r[name] is not None}
        types.append(seen.pop() if len(seen) == 1 else TYPE_VARCHAR)
    parts = [struct.pack("!I", RESULT_ROWS),
             struct.pack("!I", 0x0001),  # global_tables_spec
             struct.pack("!I", len(columns)),
             _string(keyspace), _string("t")]
    for name, type_id in zip(columns, types):
        parts.append(_string(name) + struct.pack("!H", type_id))
    parts.append(struct.pack("!I", len(rows)))
    for row in rows:
        for name, type_id in zip(columns, types):
            value = row[name]
            if value is None:
                parts.append(struct.pack("!i", -1))
            else:
                natural, data = _encode_value(value)
                if natural != type_id:  # mixed column: send as text
                    data = str(value).encode()
                parts.append(struct.pack("!i", len(data)) + data)
    return b"".join(parts)


class MiniCassandraServer:
    """Server half of the CQL native protocol over the embedded
    :class:`~gofr_tpu.datasource.columnar.Cassandra` adapter. With a
    ``password`` set it demands the PlainText SASL exchange and
    verifies it, like a PasswordAuthenticator-configured cluster."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 keyspace: str = "default", user: str = "cassandra",
                 password: str = "") -> None:
        self.host = host
        self.port = port
        self.keyspace = keyspace
        self.user = user
        self.password = password
        self.store = Cassandra(keyspace=keyspace)
        self.store.connect()
        self._server: socketserver.ThreadingTCPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = TCP((self.host, self.port), _CQLHandler)
        self._server.mini = self  # the handler reads this back
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="mini-cassandra")
        self._thread.start()

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self.store.close()
