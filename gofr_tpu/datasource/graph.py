"""Graph family: Dgraph-, ArangoDB- and SurrealDB-shaped stores over
one embedded property-graph engine.

Reference interfaces: Dgraph container/datasources.go:408-499 (query /
mutate / alter), ArangoDB :637-706 (databases, collections, documents,
edge collections, graph traversal), SurrealDB :302-344 (record ids
``table:id``, query/create/update/delete). Each adapter exposes its
store's native surface over :class:`GraphEngine`; a production
deployment swaps the engine for a network client behind the same
interface.
"""

from __future__ import annotations

import copy
import itertools
import threading
from typing import Any

from . import Instrumented


class GraphError(Exception):
    pass


class NodeNotFound(GraphError):
    pass


class GraphEngine:
    """Embedded property graph: nodes with attributes, labeled edges."""

    def __init__(self) -> None:
        self._nodes: dict[str, dict] = {}
        self._edges: dict[str, list[tuple[str, str]]] = {}  # label -> [(from,to)]
        self._lock = threading.RLock()
        self._ids = itertools.count(1)

    def put_node(self, node_id: str | None, attrs: dict) -> str:
        with self._lock:
            if node_id is None:
                node_id = f"0x{next(self._ids):x}"
            node = self._nodes.setdefault(node_id, {})
            node.update(copy.deepcopy(attrs))
            return node_id

    def get_node(self, node_id: str) -> dict:
        with self._lock:
            if node_id not in self._nodes:
                raise NodeNotFound(node_id)
            return copy.deepcopy(self._nodes[node_id])

    def delete_node(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)
            for label in self._edges:
                self._edges[label] = [
                    (f, t) for f, t in self._edges[label]
                    if f != node_id and t != node_id]

    def add_edge(self, label: str, from_id: str, to_id: str) -> None:
        with self._lock:
            for node_id in (from_id, to_id):
                if node_id not in self._nodes:
                    raise NodeNotFound(node_id)
            self._edges.setdefault(label, []).append((from_id, to_id))

    def out_neighbors(self, node_id: str, label: str) -> list[str]:
        with self._lock:
            return [t for f, t in self._edges.get(label, []) if f == node_id]

    def find_nodes(self, flt: dict) -> list[tuple[str, dict]]:
        with self._lock:
            return [(nid, copy.deepcopy(n)) for nid, n in self._nodes.items()
                    if all(n.get(k) == v for k, v in flt.items())]

    def traverse(self, start: str, label: str, depth: int) -> list[str]:
        """BFS over one edge label up to ``depth`` hops (Arango-style)."""
        seen, frontier, order = {start}, [start], []
        for _ in range(depth):
            nxt = []
            for nid in frontier:
                for t in self.out_neighbors(nid, label):
                    if t not in seen:
                        seen.add(t)
                        order.append(t)
                        nxt.append(t)
            frontier = nxt
        return order

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"nodes": len(self._nodes),
                    "edges": sum(len(v) for v in self._edges.values())}


class _GraphStore(Instrumented):
    backend_name = "graph"

    def __init__(self, engine: GraphEngine | None = None) -> None:
        self.engine = engine if engine is not None else GraphEngine()

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.debug(f"connected {self.backend_name} store")

    def health_check(self) -> dict[str, Any]:
        return {"status": "UP", "details": {"backend": self.backend_name,
                                            **self.engine.stats()}}

    def close(self) -> None:
        pass


class Dgraph(_GraphStore):
    """Dgraph-shaped surface (reference container/datasources.go:408-499):
    ``mutate`` set-nquad-style dicts, ``query`` by attribute filter,
    ``alter`` (schema ops are accepted and recorded)."""

    metric = "app_dgraph_stats"
    log_tag = "DGRAPH"
    backend_name = "dgraph"

    def __init__(self, engine: GraphEngine | None = None) -> None:
        super().__init__(engine)
        self.schema: list[str] = []

    def mutate(self, set_json: dict | list[dict]) -> dict[str, str]:
        """Insert nodes; list-valued attrs of dicts become edges.
        Returns assigned uids keyed by client-side "uid" markers."""
        docs = set_json if isinstance(set_json, list) else [set_json]
        def op():
            uids: dict[str, str] = {}
            for doc in docs:
                scalar = {k: v for k, v in doc.items()
                          if not isinstance(v, (dict, list)) and k != "uid"}
                marker = doc.get("uid")
                node_id = self.engine.put_node(
                    marker if marker and not str(marker).startswith("_:")
                    else None, scalar)
                if marker:
                    uids[str(marker).lstrip("_:")] = node_id
                for key, value in doc.items():
                    children = (value if isinstance(value, list)
                                else [value] if isinstance(value, dict) else [])
                    for child in children:
                        if not isinstance(child, dict):
                            continue
                        child_id = self.engine.put_node(
                            None, {k: v for k, v in child.items()
                                   if not isinstance(v, (dict, list))})
                        self.engine.add_edge(key, node_id, child_id)
            return uids
        return self._observed("MUTATE", f"{len(docs)} docs", op)

    def query(self, flt: dict, expand: str | None = None) -> list[dict]:
        def op():
            out = []
            for nid, attrs in self.engine.find_nodes(flt):
                attrs["uid"] = nid
                if expand:
                    attrs[expand] = [
                        dict(self.engine.get_node(t), uid=t)
                        for t in self.engine.out_neighbors(nid, expand)]
                out.append(attrs)
            return out
        return self._observed("QUERY", str(sorted(flt)), op)

    def alter(self, schema: str) -> None:
        self._observed("ALTER", schema[:40],
                       lambda: self.schema.append(schema))


class ArangoDB(_GraphStore):
    """ArangoDB-shaped surface (reference container/datasources.go:637-706):
    document collections + edge collections + graph traversal, all in
    one engine (documents are nodes tagged with their collection)."""

    metric = "app_arangodb_stats"
    log_tag = "ARANGO"
    backend_name = "arangodb"

    def create_document(self, collection: str, document: dict) -> str:
        return self._observed(
            "CREATE_DOC", collection,
            lambda: self.engine.put_node(
                None, dict(document, _collection=collection)))

    def get_document(self, collection: str, doc_id: str) -> dict:
        def op():
            doc = self.engine.get_node(doc_id)
            if doc.get("_collection") != collection:
                raise NodeNotFound(f"{collection}/{doc_id}")
            doc.pop("_collection", None)
            return doc
        return self._observed("GET_DOC", collection, op)

    def update_document(self, collection: str, doc_id: str,
                        changes: dict) -> None:
        def op():
            self.get_document(collection, doc_id)  # existence check
            self.engine.put_node(doc_id, changes)
        self._observed("UPDATE_DOC", collection, op)

    def delete_document(self, collection: str, doc_id: str) -> None:
        self._observed("DELETE_DOC", collection,
                       lambda: self.engine.delete_node(doc_id))

    def create_edge_document(self, edge_collection: str, from_id: str,
                             to_id: str) -> None:
        self._observed(
            "CREATE_EDGE", edge_collection,
            lambda: self.engine.add_edge(edge_collection, from_id, to_id))

    def query(self, collection: str, flt: dict | None = None) -> list[dict]:
        def op():
            out = []
            for nid, attrs in self.engine.find_nodes(
                    dict(flt or {}, _collection=collection)):
                attrs.pop("_collection", None)
                attrs["_id"] = nid
                out.append(attrs)
            return out
        return self._observed("QUERY", collection, op)

    def traversal(self, start_id: str, edge_collection: str,
                  depth: int = 1) -> list[dict]:
        def op():
            out = []
            for nid in self.engine.traverse(start_id, edge_collection, depth):
                doc = self.engine.get_node(nid)
                doc.pop("_collection", None)
                doc["_id"] = nid
                out.append(doc)
            return out
        return self._observed("TRAVERSAL", edge_collection, op)


class SurrealDB(_GraphStore):
    """SurrealDB-shaped surface (reference container/datasources.go:302-344):
    record ids ``table:id``, create/select/update/delete/query."""

    metric = "app_surrealdb_stats"
    log_tag = "SURREAL"
    backend_name = "surrealdb"

    @staticmethod
    def _split(thing: str) -> tuple[str, str | None]:
        table, _, rid = thing.partition(":")
        return table, (rid or None)

    def create(self, thing: str, data: dict) -> dict:
        table, rid = self._split(thing)
        def op():
            node_id = self.engine.put_node(
                f"{table}:{rid}" if rid else None,
                dict(data, _table=table))
            if not rid:  # engine-assigned: normalize to table:id form
                attrs = self.engine.get_node(node_id)
                self.engine.delete_node(node_id)
                node_id = f"{table}:{node_id.lstrip('0x')}"
                self.engine.put_node(node_id, attrs)
            doc = self.engine.get_node(node_id)
            doc.pop("_table", None)
            doc["id"] = node_id
            return doc
        return self._observed("CREATE", table, op)

    def select(self, thing: str) -> list[dict]:
        table, rid = self._split(thing)
        def op():
            if rid:
                doc = self.engine.get_node(thing)
                doc.pop("_table", None)
                doc["id"] = thing
                return [doc]
            out = []
            for nid, attrs in self.engine.find_nodes({"_table": table}):
                attrs.pop("_table", None)
                attrs["id"] = nid
                out.append(attrs)
            return out
        return self._observed("SELECT", table, op)

    def update(self, thing: str, data: dict) -> dict:
        table, rid = self._split(thing)
        if not rid:
            raise GraphError("update requires table:id")
        def op():
            self.engine.get_node(thing)  # existence check
            self.engine.put_node(thing, data)
            doc = self.engine.get_node(thing)
            doc.pop("_table", None)
            doc["id"] = thing
            return doc
        return self._observed("UPDATE", table, op)

    def delete(self, thing: str) -> None:
        table, rid = self._split(thing)
        def op():
            if rid:
                self.engine.delete_node(thing)
            else:
                for nid, _ in self.engine.find_nodes({"_table": table}):
                    self.engine.delete_node(nid)
        self._observed("DELETE", table, op)

    def query(self, table: str, flt: dict | None = None) -> list[dict]:
        def op():
            out = []
            for nid, attrs in self.engine.find_nodes(
                    dict(flt or {}, _table=table)):
                attrs.pop("_table", None)
                attrs["id"] = nid
                out.append(attrs)
            return out
        return self._observed("QUERY", table, op)
