"""Google Cloud Storage network client speaking the JSON API, plus a
mini server.

The reference's GCS module is a driver-backed network client
(datasource/file/gcs over cloud.google.com/go/storage). This client
speaks the storage JSON API directly — media upload
(``POST /upload/storage/v1/b/{bucket}/o?uploadType=media``), media
download (``?alt=media``), object list with ``items``/``nextPageToken``
pagination, delete — with Bearer-token auth, behind the same method
surface as the embedded
:class:`~gofr_tpu.datasource.object_store.GCSFileSystem` adapter, so
swapping is a constructor change.

:class:`MiniGCSServer` serves those endpoints over the embedded
adapter on the framework's HTTP server and rejects requests whose
Bearer token doesn't match — auth failures look like real GCS (401).
"""

from __future__ import annotations

import datetime as _dt
import json
import urllib.parse
import urllib.request
from typing import Any

from . import Instrumented
from .miniserver import ThreadedHTTPMiniServer
from .object_store import GCSFileSystem, ObjectNotFound, ObjectStoreEngine

# real GCS truncates listings at 1000 items per page
_PAGE_SIZE = 1000


class GCSError(Exception):
    pass


class GCSWire(Instrumented):
    """JSON-API client with the embedded adapter's verbs
    (upload/download/list_blobs, plus delete/exists)."""

    metric = "app_gcs_stats"
    log_tag = "GCS"

    def __init__(self, *, endpoint: str = "https://storage.googleapis.com",
                 bucket: str = "gofr", token: str = "",
                 timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.token = token
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to GCS", endpoint=self.endpoint,
                             bucket=self.bucket)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, method: str, path: str,
              body: bytes | None = None) -> tuple[int, bytes]:
        headers = {"Content-Type": "application/octet-stream"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(self.endpoint + path, data=body,
                                     method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read()

    @staticmethod
    def _object_path(name: str) -> str:
        return urllib.parse.quote(name, safe="")

    # ----------------------------------------------------- native verbs
    def upload(self, name: str, data: bytes) -> None:
        def op():
            qs = urllib.parse.urlencode(
                {"uploadType": "media", "name": name})
            status, payload = self._call(
                "POST", f"/upload/storage/v1/b/{self.bucket}/o?{qs}",
                body=data)
            if status != 200:
                raise GCSError(f"upload {name} -> {status}: {payload[:200]!r}")
        self._observed("UPLOAD", name, op)

    def download(self, name: str) -> bytes:
        def op():
            status, payload = self._call(
                "GET", f"/storage/v1/b/{self.bucket}/o/"
                       f"{self._object_path(name)}?alt=media")
            if status == 404:
                raise ObjectNotFound(f"{self.bucket}/{name}")
            if status != 200:
                raise GCSError(
                    f"download {name} -> {status}: {payload[:200]!r}")
            return payload
        return self._observed("DOWNLOAD", name, op)

    def delete(self, name: str) -> None:
        def op():
            status, payload = self._call(
                "DELETE", f"/storage/v1/b/{self.bucket}/o/"
                          f"{self._object_path(name)}")
            if status == 404:
                raise ObjectNotFound(f"{self.bucket}/{name}")
            if status not in (200, 204):
                raise GCSError(f"delete {name} -> {status}: {payload[:200]!r}")
        self._observed("DELETE", name, op)

    def exists(self, name: str) -> bool:
        def op():
            status, payload = self._call(
                "GET", f"/storage/v1/b/{self.bucket}/o/"
                       f"{self._object_path(name)}")
            if status == 200:
                return True
            if status == 404:
                return False
            raise GCSError(f"stat {name} -> {status}: {payload[:200]!r}")
        return self._observed("STAT", name, op)

    def list_blobs(self, prefix: str = "") -> list[str]:
        def op():
            names: list[str] = []
            token = ""
            while True:  # follow nextPageToken to the end
                params = {"prefix": prefix}
                if token:
                    params["pageToken"] = token
                qs = urllib.parse.urlencode(params)
                status, payload = self._call(
                    "GET", f"/storage/v1/b/{self.bucket}/o?{qs}")
                if status != 200:
                    raise GCSError(f"list -> {status}: {payload[:200]!r}")
                data = json.loads(payload)
                names.extend(item["name"]
                             for item in data.get("items", []))
                token = data.get("nextPageToken", "")
                if not token:
                    return names
        return self._observed("LIST", prefix or "*", op)

    def health_check(self) -> dict[str, Any]:
        try:
            status, _ = self._call("GET",
                                   f"/storage/v1/b/{self.bucket}/o")
            return {"status": "UP" if status == 200 else "DOWN",
                    "details": {"endpoint": self.endpoint,
                                "bucket": self.bucket}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

class MiniGCSServer(ThreadedHTTPMiniServer):
    """The storage JSON API over the embedded adapter. A configured
    ``token`` is enforced: a missing or wrong Bearer is a 401."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 token: str = "") -> None:
        super().__init__(host, port)
        self.token = token
        self.engine = ObjectStoreEngine()

    def handle(self, request) -> tuple[int, bytes, str]:
        if self.token:
            got = request.headers.get("authorization", "")
            if got != f"Bearer {self.token}":
                return 401, b'{"error": {"code": 401}}', "application/json"
        try:
            return self._route(request)
        except ObjectNotFound:
            return 404, b'{"error": {"code": 404}}', "application/json"

    def _route(self, request) -> tuple[int, bytes, str]:
        path = request.path
        if path.startswith("/upload/storage/v1/b/") \
                and request.method == "POST":
            bucket = path.split("/")[5]
            name = request.param("name")
            self.engine.put(bucket, name, request.body)
            return 200, json.dumps(
                {"name": name, "bucket": bucket,
                 "size": str(len(request.body))}).encode(), \
                "application/json"
        if path.startswith("/storage/v1/b/"):
            # the framework server hands the path already URL-decoded,
            # so the object name may contain real slashes — parse by
            # prefix, not by segment count
            rest = path[len("/storage/v1/b/"):]
            bucket, _, after = rest.partition("/o")
            if after in ("", "/"):
                return self._list(bucket, request)
            if after.startswith("/"):
                name = after[1:]
                if request.method == "GET" \
                        and request.param("alt") == "media":
                    return 200, self.engine.get(bucket, name), \
                        "application/octet-stream"
                if request.method == "GET":
                    data = self.engine.get(bucket, name)  # 404 when absent
                    return 200, json.dumps(
                        {"name": name, "bucket": bucket,
                         "size": str(len(data))}).encode(), \
                        "application/json"
                if request.method == "DELETE":
                    if not self.engine.exists(bucket, name):
                        raise ObjectNotFound(name)
                    self.engine.delete(bucket, name)
                    return 204, b"", "application/json"
        return 400, b'{"error": {"code": 400}}', "application/json"

    def _list(self, bucket: str, request) -> tuple[int, bytes, str]:
        prefix = request.param("prefix")
        token = request.param("pageToken")
        rows = sorted(self.engine.list(bucket, prefix))
        if token:  # opaque token = last name of the previous page
            rows = [r for r in rows if r[0] > token]
        page, rest = rows[:_PAGE_SIZE], rows[_PAGE_SIZE:]
        out: dict[str, Any] = {
            "kind": "storage#objects",
            "items": [{"name": k, "size": str(size),
                       "updated": _dt.datetime.fromtimestamp(
                           mtime, tz=_dt.timezone.utc).isoformat()}
                      for k, size, mtime in page]}
        if rest and page:
            out["nextPageToken"] = page[-1][0]
        return 200, json.dumps(out).encode(), "application/json"
