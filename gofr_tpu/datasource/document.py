"""Document-store family: Mongo-, Elasticsearch-, Solr- and
Couchbase-shaped stores over one embedded document engine.

The reference declares a canonical interface per store in
container/datasources.go (Mongo :232, Elasticsearch :708, Solr :386,
Couchbase :748) and ships driver-backed modules for each
(datasource/mongo, datasource/elasticsearch, ...). Here each store is a
thin protocol adapter over :class:`DocumentEngine` — an embedded,
thread-safe collection-of-dicts engine — so the full API surface is
real and testable without external servers; a production deployment
swaps the engine for a network client behind the same interface.
"""

from __future__ import annotations

import copy
import itertools
import threading
from typing import Any, Iterable

from . import Instrumented


class DocumentError(Exception):
    pass


class DocumentNotFound(DocumentError):
    pass


def _matches(doc: dict, flt: dict) -> bool:
    """Mongo-style filter: equality plus $gt/$gte/$lt/$lte/$ne/$in."""
    for key, cond in flt.items():
        value = doc.get(key)
        if isinstance(cond, dict):
            for op, operand in cond.items():
                if op == "$gt" and not (value is not None and value > operand):
                    return False
                elif op == "$gte" and not (value is not None and value >= operand):
                    return False
                elif op == "$lt" and not (value is not None and value < operand):
                    return False
                elif op == "$lte" and not (value is not None and value <= operand):
                    return False
                elif op == "$ne" and value == operand:
                    return False
                elif op == "$in" and value not in operand:
                    return False
        elif value != cond:
            return False
    return True


class DocumentEngine:
    """Embedded collections-of-dicts store with Mongo-style filters."""

    def __init__(self) -> None:
        self._collections: dict[str, dict[Any, dict]] = {}
        self._lock = threading.RLock()
        self._ids = itertools.count(1)

    def insert(self, collection: str, doc: dict, doc_id: Any = None) -> Any:
        with self._lock:
            coll = self._collections.setdefault(collection, {})
            if doc_id is None:
                doc_id = doc.get("_id")
            if doc_id is None:
                doc_id = next(self._ids)
            if doc_id in coll:
                raise DocumentError(f"duplicate id {doc_id!r} in {collection}")
            stored = copy.deepcopy(doc)
            stored["_id"] = doc_id
            coll[doc_id] = stored
            return doc_id

    def upsert(self, collection: str, doc_id: Any, doc: dict) -> None:
        with self._lock:
            coll = self._collections.setdefault(collection, {})
            stored = copy.deepcopy(doc)
            stored["_id"] = doc_id
            coll[doc_id] = stored

    def get(self, collection: str, doc_id: Any) -> dict:
        with self._lock:
            coll = self._collections.get(collection, {})
            if doc_id not in coll:
                raise DocumentNotFound(f"{collection}/{doc_id}")
            return copy.deepcopy(coll[doc_id])

    def find(self, collection: str, flt: dict | None = None,
             limit: int | None = None) -> list[dict]:
        with self._lock:
            docs = list(self._collections.get(collection, {}).values())
        out = [copy.deepcopy(d) for d in docs
               if flt is None or _matches(d, flt)]
        return out[:limit] if limit is not None else out

    def update(self, collection: str, flt: dict, changes: dict) -> int:
        with self._lock:
            coll = self._collections.get(collection, {})
            n = 0
            for doc in coll.values():
                if _matches(doc, flt):
                    doc.update(copy.deepcopy(changes))
                    n += 1
            return n

    def delete(self, collection: str, flt: dict) -> int:
        with self._lock:
            coll = self._collections.get(collection, {})
            victims = [k for k, d in coll.items() if _matches(d, flt)]
            for k in victims:
                del coll[k]
            return len(victims)

    def drop(self, collection: str) -> None:
        with self._lock:
            self._collections.pop(collection, None)

    def collections(self) -> list[str]:
        with self._lock:
            return sorted(self._collections)

    def count(self, collection: str) -> int:
        with self._lock:
            return len(self._collections.get(collection, {}))


class _DocumentStore(Instrumented):
    """Shared provider/health plumbing for the family."""

    backend_name = "document"

    def __init__(self, engine: DocumentEngine | None = None) -> None:
        self.engine = engine if engine is not None else DocumentEngine()
        self._connected = False

    def connect(self) -> None:
        self._connected = True
        if self.logger is not None:
            self.logger.debug(f"connected {self.backend_name} store")

    def health_check(self) -> dict[str, Any]:
        return {"status": "UP",
                "details": {"backend": self.backend_name,
                            "collections": len(self.engine.collections())}}

    def close(self) -> None:
        self._connected = False


class Mongo(_DocumentStore):
    """Mongo-shaped API (reference container/datasources.go:232-300)."""

    metric = "app_mongo_stats"
    log_tag = "MONGO"
    backend_name = "mongo"

    def insert_one(self, collection: str, document: dict) -> Any:
        return self._observed("INSERT", collection,
                              lambda: self.engine.insert(collection, document))

    def insert_many(self, collection: str, documents: Iterable[dict]) -> list:
        docs = list(documents)
        return self._observed(
            "INSERT_MANY", collection,
            lambda: [self.engine.insert(collection, d) for d in docs])

    def find(self, collection: str, flt: dict | None = None,
             limit: int | None = None) -> list[dict]:
        return self._observed("FIND", collection,
                              lambda: self.engine.find(collection, flt, limit))

    def find_one(self, collection: str, flt: dict | None = None) -> dict | None:
        def op():
            hits = self.engine.find(collection, flt, limit=1)
            return hits[0] if hits else None
        return self._observed("FIND_ONE", collection, op)

    def update_many(self, collection: str, flt: dict, update: dict) -> int:
        changes = update.get("$set", update)
        return self._observed(
            "UPDATE", collection,
            lambda: self.engine.update(collection, flt, changes))

    update_one = update_many

    def delete_many(self, collection: str, flt: dict) -> int:
        return self._observed("DELETE", collection,
                              lambda: self.engine.delete(collection, flt))

    delete_one = delete_many

    def count_documents(self, collection: str, flt: dict | None = None) -> int:
        return len(self.find(collection, flt))

    def drop(self, collection: str) -> None:
        self._observed("DROP", collection,
                       lambda: self.engine.drop(collection))


def _tokenize(text: str) -> set[str]:
    return {t for t in "".join(c.lower() if c.isalnum() else " "
                               for c in text).split() if t}


class Elasticsearch(_DocumentStore):
    """Elasticsearch-shaped API (reference container/datasources.go:708-746):
    index/get/delete documents plus a match query with naive token
    scoring (hits sorted by overlap count)."""

    metric = "app_elasticsearch_stats"
    log_tag = "ES"
    backend_name = "elasticsearch"

    def index(self, index: str, doc_id: Any, document: dict) -> None:
        self._observed("INDEX", index,
                       lambda: self.engine.upsert(index, doc_id, document))

    def get(self, index: str, doc_id: Any) -> dict:
        return self._observed("GET", index,
                              lambda: self.engine.get(index, doc_id))

    def delete(self, index: str, doc_id: Any) -> None:
        self._observed("DELETE", index,
                       lambda: self.engine.delete(index, {"_id": doc_id}))

    def search(self, index: str, query: dict | None = None,
               size: int = 10) -> dict:
        """Supports {"match": {field: text}}, {"term": {field: v}}, and
        {"match_all": {}} queries; returns the ES hits envelope."""
        def op():
            docs = self.engine.find(index)
            if not query or "match_all" in query:
                scored = [(1.0, d) for d in docs]
            elif "term" in query:
                ((field, value),) = query["term"].items()
                scored = [(1.0, d) for d in docs if d.get(field) == value]
            elif "match" in query:
                ((field, text),) = query["match"].items()
                wanted = _tokenize(str(text))
                scored = []
                for d in docs:
                    overlap = len(wanted & _tokenize(str(d.get(field, ""))))
                    if overlap:
                        scored.append((float(overlap), d))
                scored.sort(key=lambda p: -p[0])
            else:
                raise DocumentError(f"unsupported query: {sorted(query)}")
            hits = [{"_index": index, "_id": d["_id"], "_score": s,
                     "_source": {k: v for k, v in d.items() if k != "_id"}}
                    for s, d in scored[:size]]
            return {"hits": {"total": {"value": len(scored)}, "hits": hits}}
        return self._observed("SEARCH", index, op)

    def bulk(self, index: str, documents: Iterable[tuple[Any, dict]]) -> int:
        docs = list(documents)
        def op():
            for doc_id, doc in docs:
                self.engine.upsert(index, doc_id, doc)
            return len(docs)
        return self._observed("BULK", index, op)


class Solr(_DocumentStore):
    """Solr-shaped API (reference container/datasources.go:386-406):
    add/search/delete against named cores."""

    metric = "app_solr_stats"
    log_tag = "SOLR"
    backend_name = "solr"

    def add(self, core: str, documents: Iterable[dict]) -> int:
        docs = list(documents)
        def op():
            for d in docs:
                self.engine.upsert(core, d.get("id", d.get("_id")), d)
            return len(docs)
        return self._observed("ADD", core, op)

    def search(self, core: str, query: str, rows: int = 10) -> dict:
        """`field:value` or bare-text query over all fields."""
        def op():
            docs = self.engine.find(core)
            if query in ("*", "*:*"):
                hits = docs
            elif ":" in query:
                field, value = query.split(":", 1)
                hits = [d for d in docs if str(d.get(field)) == value]
            else:
                wanted = _tokenize(query)
                hits = [d for d in docs
                        if wanted & _tokenize(" ".join(map(str, d.values())))]
            return {"response": {"numFound": len(hits),
                                 "docs": hits[:rows]}}
        return self._observed("SEARCH", core, op)

    def delete(self, core: str, doc_id: Any) -> None:
        self._observed("DELETE", core,
                       lambda: self.engine.delete(core, {"_id": doc_id}))


class Couchbase(_DocumentStore):
    """Couchbase-shaped API (reference container/datasources.go:748-788):
    bucket get/upsert/remove plus N1QL-lite query over a bucket."""

    metric = "app_couchbase_stats"
    log_tag = "CB"
    backend_name = "couchbase"

    def get(self, bucket: str, key: str) -> dict:
        return self._observed("GET", bucket,
                              lambda: self.engine.get(bucket, key))

    def upsert(self, bucket: str, key: str, document: dict) -> None:
        self._observed("UPSERT", bucket,
                       lambda: self.engine.upsert(bucket, key, document))

    def insert(self, bucket: str, key: str, document: dict) -> None:
        self._observed(
            "INSERT", bucket,
            lambda: self.engine.insert(bucket, document, doc_id=key))

    def remove(self, bucket: str, key: str) -> None:
        def op():
            if not self.engine.delete(bucket, {"_id": key}):
                raise DocumentNotFound(f"{bucket}/{key}")
        self._observed("REMOVE", bucket, op)

    def query(self, bucket: str, flt: dict | None = None) -> list[dict]:
        return self._observed("QUERY", bucket,
                              lambda: self.engine.find(bucket, flt))
