"""DynamoDB network client speaking the JSON 1.0 API with SigV4
signing, plus a signature-verifying mini server.

The reference ships a DynamoDB-backed KV store module
(datasource/kv-store/dynamodb over aws-sdk-go). This client speaks the
service's wire surface directly — ``POST /`` with
``X-Amz-Target: DynamoDB_20120810.<Op>`` and
``application/x-amz-json-1.0`` bodies (GetItem/PutItem/DeleteItem/
Scan), signed with the same from-spec SigV4 chain the S3 client uses
(:func:`~gofr_tpu.datasource.s3_wire.sign_v4`, ``service="dynamodb"``)
— behind the framework's KV surface (get/set/delete/keys), so it slots
into the container's ``kv`` slot interchangeably with
:class:`~gofr_tpu.datasource.kv.InMemoryKV`.

:class:`MiniDynamoServer` verifies every request's SigV4 signature
against the configured credentials and serves the four targets over an
in-process table — a wrong secret is a 403, like real AWS.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request
from typing import Any

from .kv import KeyNotFound, KVError, _Instrumented
from .miniserver import ThreadedHTTPMiniServer
from .s3_wire import sign_v4

_TARGET_PREFIX = "DynamoDB_20120810."
_CONTENT_TYPE = "application/x-amz-json-1.0"


class DynamoError(KVError):
    pass


class DynamoKV(_Instrumented):
    """SigV4-signed DynamoDB client behind the KV surface. String
    values live in attribute ``v`` under partition key ``k``; every op
    records into ``app_kv_stats`` like the other KV backends."""

    def __init__(self, *, endpoint: str = "https://dynamodb.us-east-1.amazonaws.com",
                 table: str = "gofr_kv", access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.table = table
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to dynamodb",
                             endpoint=self.endpoint, table=self.table)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, target: str, body: dict) -> tuple[int, dict]:
        payload = json.dumps(body).encode()
        host = urllib.parse.urlsplit(self.endpoint).netloc
        headers = sign_v4(
            "POST", "/", {},
            {"host": host, "x-amz-target": _TARGET_PREFIX + target,
             "content-type": _CONTENT_TYPE},
            payload, access_key=self.access_key,
            secret_key=self.secret_key, region=self.region,
            service="dynamodb")
        req = urllib.request.Request(self.endpoint + "/", data=payload,
                                     method="POST", headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as exc:
            data = exc.read()
            try:
                return exc.code, json.loads(data or b"{}")
            except json.JSONDecodeError:
                return exc.code, {"message": data.decode("utf-8", "replace")}

    def _checked(self, target: str, body: dict) -> dict:
        status, data = self._call(target, body)
        if status != 200:
            raise DynamoError(
                f"{target} -> {status}: {data.get('message', data)}")
        return data

    # --------------------------------------------------------- KV verbs
    def get(self, key: str) -> str:
        def op():
            data = self._checked("GetItem", {
                "TableName": self.table,
                "Key": {"k": {"S": key}}, "ConsistentRead": True})
            item = data.get("Item")
            if not item:
                raise KeyNotFound(key)
            return item["v"]["S"]
        return self._observed("GET", key, op)

    def set(self, key: str, value: str) -> None:
        def op():
            self._checked("PutItem", {
                "TableName": self.table,
                "Item": {"k": {"S": key}, "v": {"S": str(value)}}})
        self._observed("SET", key, op)

    def delete(self, key: str) -> None:
        # idempotent like the other KV backends: deleting an absent
        # key is a no-op, not an error
        def op():
            self._checked("DeleteItem", {
                "TableName": self.table, "Key": {"k": {"S": key}}})
        self._observed("DELETE", key, op)

    def keys(self) -> list[str]:
        def op():
            out: list[str] = []
            start: dict | None = None
            while True:  # follow LastEvaluatedKey pagination to the end
                body: dict[str, Any] = {"TableName": self.table,
                                        "ProjectionExpression": "k"}
                if start:
                    body["ExclusiveStartKey"] = start
                data = self._checked("Scan", body)
                out.extend(item["k"]["S"]
                           for item in data.get("Items", []))
                start = data.get("LastEvaluatedKey")
                if not start:
                    return sorted(out)
        return self._observed("KEYS", "*", op)

    def health_check(self) -> dict[str, Any]:
        try:
            self._checked("Scan", {"TableName": self.table, "Limit": 1})
            return {"status": "UP",
                    "details": {"endpoint": self.endpoint,
                                "table": self.table}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

# real DynamoDB pages Scan responses at 1MB; the mini server pages by
# item count so the client's pagination loop is exercised
_SCAN_PAGE = 1000


class MiniDynamoServer(ThreadedHTTPMiniServer):
    """The four DynamoDB targets over an in-process table, with SigV4
    verification against the configured credentials."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 access_key: str = "test", secret_key: str = "secret",
                 region: str = "us-east-1") -> None:
        super().__init__(host, port)
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.tables: dict[str, dict[str, dict]] = {}
        self._lock = threading.Lock()

    def _verify(self, request) -> bool:
        import datetime as _dt
        import hmac as _hmac
        auth = request.headers.get("authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            return False
        try:
            fields = dict(part.strip().split("=", 1)
                          for part in auth[17:].split(","))
            signed_headers = fields["SignedHeaders"].split(";")
            got_signature = fields["Signature"]
            access_key = fields["Credential"].split("/")[0]
            when = _dt.datetime.strptime(
                request.headers.get("x-amz-date", ""),
                "%Y%m%dT%H%M%SZ").replace(tzinfo=_dt.timezone.utc)
        except (KeyError, ValueError):
            return False
        if access_key != self.access_key:
            return False
        headers = {name: request.headers.get(name, "")
                   for name in signed_headers}
        expect = sign_v4("POST", request.path,
                         {k: v[0] for k, v in request.query.items()},
                         headers, request.body,
                         access_key=self.access_key,
                         secret_key=self.secret_key, region=self.region,
                         service="dynamodb", when=when)
        expect_sig = expect["authorization"].rsplit("Signature=", 1)[-1]
        return _hmac.compare_digest(expect_sig, got_signature)

    def handle(self, request) -> tuple[int, bytes, str]:
        if not self._verify(request):
            return 403, json.dumps(
                {"__type": "InvalidSignatureException",
                 "message": "signature mismatch"}).encode(), _CONTENT_TYPE
        target = request.headers.get("x-amz-target", "")
        if not target.startswith(_TARGET_PREFIX):
            return 400, b'{"message": "bad target"}', _CONTENT_TYPE
        op = target[len(_TARGET_PREFIX):]
        body = json.loads(request.body or b"{}")
        table = self.tables.setdefault(body.get("TableName", ""), {})
        with self._lock:
            if op == "PutItem":
                item = body["Item"]
                table[item["k"]["S"]] = item
                return 200, b"{}", _CONTENT_TYPE
            if op == "GetItem":
                item = table.get(body["Key"]["k"]["S"])
                out = {"Item": item} if item else {}
                return 200, json.dumps(out).encode(), _CONTENT_TYPE
            if op == "DeleteItem":
                item = table.pop(body["Key"]["k"]["S"], None)
                out = {"Attributes": item} if item else {}
                return 200, json.dumps(out).encode(), _CONTENT_TYPE
            if op == "Scan":
                rows = sorted(table.items())
                start = body.get("ExclusiveStartKey")
                if start:
                    after = start["k"]["S"]
                    rows = [r for r in rows if r[0] > after]
                limit = min(int(body.get("Limit", _SCAN_PAGE)), _SCAN_PAGE)
                page, rest = rows[:limit], rows[limit:]
                out = {"Items": [item for _, item in page],
                       "Count": len(page)}
                if rest and page:
                    out["LastEvaluatedKey"] = {"k": {"S": page[-1][0]}}
                return 200, json.dumps(out).encode(), _CONTENT_TYPE
        return 400, json.dumps(
            {"message": f"unsupported op {op}"}).encode(), _CONTENT_TYPE
