"""SurrealDB network client speaking the WebSocket JSON-RPC protocol,
plus a mini server.

The reference's SurrealDB module is a driver-backed network client
(container/datasources.go:302-344 over surrealdb.go). This client
speaks the database's WS surface directly — RFC 6455 upgrade to
``/rpc`` (the framework's own websocket layer), then JSON-RPC:
``signin`` → ``use`` → ``create``/``select``/``update``/``delete``/
``query`` with request-id-matched responses — behind the same method
surface as the embedded
:class:`~gofr_tpu.datasource.graph.SurrealDB` adapter, so swapping is
a constructor change. ``query`` generates real SurrealQL
(``SELECT * FROM type::table($tb) WHERE field = $field``) with bound
variables.

:class:`MiniSurrealServer` is a framework :class:`~gofr_tpu.app.App`
serving ``/rpc`` over the same websocket runtime — per-connection
signin state, the RPC method set, and the SurrealQL subset the client
emits.
"""

from __future__ import annotations

import asyncio
import itertools
import re
import threading
from typing import Any

from . import Instrumented
from .graph import GraphEngine, GraphError, SurrealDB


class SurrealWireError(GraphError):
    pass


class SurrealWire(Instrumented):
    """WS JSON-RPC client with the embedded adapter's verbs
    (create/select/update/delete/query)."""

    metric = "app_surrealdb_stats"
    log_tag = "SURREAL"

    def __init__(self, *, endpoint: str = "ws://localhost:8000/rpc",
                 namespace: str = "app", database: str = "app",
                 username: str = "root", password: str = "",
                 timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "ws://" + endpoint
        if not endpoint.endswith("/rpc"):
            endpoint = endpoint.rstrip("/") + "/rpc"
        self.endpoint = endpoint
        self.namespace = namespace
        self.database = database
        self.username = username
        self.password = password
        self.timeout_s = timeout_s
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._conn: Any = None
        self._ids = itertools.count(1)
        self._lock = threading.RLock()

    # ------------------------------------------------------------ lifecycle
    def connect(self) -> None:
        if self._loop is not None:
            self.close()
        ready = threading.Event()

        def run() -> None:
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)
            ready.set()
            try:
                loop.run_forever()
            finally:
                loop.close()  # release the selector fd

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="surreal-wire")
        self._thread.start()
        ready.wait(5)

        try:
            from ..websocket.service import connect as ws_connect
            self._conn = self._run(ws_connect(self.endpoint,
                                              timeout=self.timeout_s))
            if self.username:
                self._rpc("signin", [{"user": self.username,
                                      "pass": self.password}])
            self._rpc("use", [self.namespace, self.database])
        except BaseException:
            # a failed connect must not strand the loop thread — each
            # reconnect attempt would otherwise leak a thread + fd
            self.close()
            raise
        if self.logger is not None:
            self.logger.info("connected to surrealdb",
                             endpoint=self.endpoint, ns=self.namespace,
                             db=self.database)

    def _run(self, coro):
        assert self._loop is not None
        future = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return future.result(self.timeout_s)

    def _rpc(self, method: str, params: list[Any]) -> Any:
        with self._lock:
            if self._conn is None:
                raise SurrealWireError("not connected; call connect() first")
            req_id = next(self._ids)

            async def round_trip():
                await self._conn.send({"id": req_id, "method": method,
                                       "params": params})
                while True:
                    message = await self._conn.recv()
                    if message is None:
                        raise SurrealWireError("connection closed")
                    import json
                    payload = json.loads(message.text())
                    if payload.get("id") == req_id:
                        return payload

            try:
                payload = self._run(round_trip())
            except (OSError, TimeoutError, asyncio.TimeoutError) as exc:
                self.close()  # poisoned stream: unconsumed responses
                raise SurrealWireError(
                    f"connection lost mid-call ({exc})") from exc
        if "error" in payload and payload["error"]:
            err = payload["error"]
            raise SurrealWireError(
                f"{err.get('message', err)} (code {err.get('code')})")
        return payload.get("result")

    def close(self) -> None:
        loop, conn = self._loop, self._conn
        self._conn = None
        self._loop = None
        if loop is not None:
            if conn is not None:
                try:
                    asyncio.run_coroutine_threadsafe(
                        conn.close(), loop).result(2)
                except Exception:
                    pass
            loop.call_soon_threadsafe(loop.stop)
            if self._thread is not None:
                self._thread.join(5)
                self._thread = None

    # ----------------------------------------------------- native verbs
    def create(self, thing: str, data: dict) -> dict:
        return self._observed(
            "CREATE", thing.partition(":")[0],
            lambda: self._rpc("create", [thing, data]))

    def select(self, thing: str) -> list[dict]:
        def op():
            result = self._rpc("select", [thing])
            return result if isinstance(result, list) else [result]
        return self._observed("SELECT", thing.partition(":")[0], op)

    def update(self, thing: str, data: dict) -> dict:
        return self._observed(
            "UPDATE", thing.partition(":")[0],
            lambda: self._rpc("update", [thing, data]))

    def delete(self, thing: str) -> None:
        self._observed("DELETE", thing.partition(":")[0],
                       lambda: self._rpc("delete", [thing]))

    def query(self, table: str, flt: dict | None = None) -> list[dict]:
        """Generates real SurrealQL with bound variables. Field names
        ride in the statement text, so they are validated — values are
        always bound."""
        def op():
            sql = "SELECT * FROM type::table($tb)"
            variables: dict[str, Any] = {"tb": table}
            for i, (key, value) in enumerate(sorted((flt or {}).items())):
                if not re.fullmatch(r"\w+", str(key)):
                    raise SurrealWireError(
                        f"invalid field name {key!r}")
                sql += (" WHERE" if i == 0 else " AND") \
                    + f" {key} = $p{i}"
                variables[f"p{i}"] = value
            result = self._rpc("query", [sql, variables])
            # surreal returns one {status, result} envelope per statement
            first = result[0] if isinstance(result, list) and result else {}
            if first.get("status") not in (None, "OK"):
                raise SurrealWireError(str(first.get("result")))
            return first.get("result", [])
        return self._observed("QUERY", table, op)

    def health_check(self) -> dict[str, Any]:
        try:
            self._rpc("ping", [])
            return {"status": "UP",
                    "details": {"endpoint": self.endpoint,
                                "ns": self.namespace,
                                "db": self.database}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

_SELECT_RE = re.compile(
    r"SELECT \* FROM type::table\(\$tb\)"
    r"(?P<where>( (?:WHERE|AND) \w+ = \$\w+)*)$")


class MiniSurrealServer:
    """A framework App serving the SurrealDB RPC surface at ``/rpc``
    over the framework's own websocket runtime. Connections must
    ``signin`` (when a password is configured) before data methods."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 username: str = "root", password: str = "") -> None:
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.store = SurrealDB(GraphEngine())
        self._app: Any = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None

    # --------------------------------------------------------- dispatch
    def _dispatch(self, conn: Any, method: str,
                  params: list[Any]) -> Any:
        if method == "ping":
            return True
        if method == "signin":
            cred = params[0] if params else {}
            if (isinstance(cred, dict)
                    and cred.get("user") == self.username
                    and cred.get("pass") == self.password):
                # auth state lives on the connection object itself —
                # conn ids are client-supplied (Sec-WebSocket-Key) and
                # therefore forgeable/collidable
                conn._surreal_authed = True
                return "token"
            raise SurrealWireError("invalid credentials")
        if method == "use":  # allowed pre-signin, like real surreal
            return None
        if self.password and not getattr(conn, "_surreal_authed", False):
            raise SurrealWireError("not signed in")
        if method == "create":
            return self.store.create(params[0], params[1])
        if method == "select":
            return self.store.select(params[0])
        if method == "update":
            return self.store.update(params[0], params[1])
        if method == "delete":
            self.store.delete(params[0])
            return None
        if method == "query":
            return self._query(params[0],
                               params[1] if len(params) > 1 else {})
        raise SurrealWireError(f"unknown method {method!r}")

    def _query(self, sql: str, variables: dict) -> list[dict]:
        match = _SELECT_RE.match(sql.strip())
        if not match or "tb" not in variables:
            raise SurrealWireError(f"unsupported SurrealQL: {sql!r}")
        flt = {}
        for cond in re.finditer(r"(\w+) = \$(\w+)", match.group("where")):
            field, var = cond.groups()
            if var not in variables:
                raise SurrealWireError(f"unbound variable ${var}")
            flt[field] = variables[var]
        rows = self.store.query(variables["tb"], flt or None)
        return [{"status": "OK", "result": rows}]

    # -------------------------------------------------------- lifecycle
    def start(self) -> None:
        from ..config import DictConfig
        from ..app import App

        app = App(config=DictConfig({"HTTP_PORT": "0", "METRICS_PORT": "0",
                                     "APP_NAME": "mini-surreal",
                                     "LOG_LEVEL": "ERROR"}))
        outer = self

        @app.websocket("/rpc")
        def rpc(ctx):
            import json
            payload = ctx.bind()
            if not isinstance(payload, dict):
                payload = json.loads(payload)
            req_id = payload.get("id")
            try:
                result = outer._dispatch(ctx._ws_conn,
                                         payload.get("method", ""),
                                         payload.get("params") or [])
                return {"id": req_id, "result": result}
            except GraphError as exc:
                return {"id": req_id,
                        "error": {"code": -32000, "message": str(exc)}}
            except Exception as exc:
                # malformed params must yield a JSON-RPC error, not a
                # dropped reply that stalls the client's recv loop
                return {"id": req_id,
                        "error": {"code": -32602,
                                  "message": f"invalid params: {exc!r}"}}

        self._app = app
        started = threading.Event()
        error: list[BaseException] = []

        def run() -> None:
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)

            async def main():
                await app.start()
                started.set()  # only after a successful bind
                await app._stop_event.wait()

            try:
                loop.run_until_complete(main())
            except BaseException as exc:  # surfaced to start()
                error.append(exc)
                started.set()  # after the append — start() reads both
            finally:
                loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="mini-surreal")
        self._thread.start()
        if not started.wait(10):
            raise SurrealWireError("mini surreal server did not start")
        if error:
            raise error[0]
        self.port = app.http_server.bound_port

    def close(self) -> None:
        if self._loop is not None and self._loop.is_running():
            asyncio.run_coroutine_threadsafe(self._app.stop(),
                                             self._loop).result(10)
        if self._thread is not None:
            self._thread.join(10)
            self._thread = None
        self._loop = None
