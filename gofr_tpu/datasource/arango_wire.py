"""ArangoDB network client speaking the HTTP document API, plus a mini
server.

The reference's ArangoDB module is a driver-backed network client
(container/datasources.go:637-706 over arangodb/go-driver). This
client speaks the database's HTTP surface directly — document CRUD
(``POST/GET/PATCH/DELETE /_db/{db}/_api/document/...``), edge
documents (``_from``/``_to``), by-example queries
(``PUT /_api/simple/by-example``), and graph traversal
(``POST /_api/traversal``) — with HTTP basic auth, behind the same
method surface as the embedded
:class:`~gofr_tpu.datasource.graph.ArangoDB` adapter, so swapping is a
constructor change.

:class:`MiniArangoServer` serves those endpoints over the embedded
adapter on the framework's HTTP server, rejecting bad credentials with
401 like a real deployment.
"""

from __future__ import annotations

import base64
import json
import urllib.parse
from typing import Any

from . import Instrumented
from ._http import json_call
from .graph import ArangoDB, GraphEngine, GraphError, NodeNotFound
from .miniserver import ThreadedHTTPMiniServer


class ArangoWireError(GraphError):
    pass


class ArangoWire(Instrumented):
    """HTTP client with the embedded adapter's verbs (create/get/
    update/delete document, edge documents, query, traversal)."""

    metric = "app_arangodb_stats"
    log_tag = "ARANGO"

    def __init__(self, *, endpoint: str = "http://localhost:8529",
                 database: str = "_system", username: str = "root",
                 password: str = "", timeout_s: float = 30.0) -> None:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.database = database
        self.username = username
        self.password = password
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to arangodb",
                             endpoint=self.endpoint, database=self.database)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, method: str, path: str,
              body: Any = None) -> tuple[int, Any]:
        token = base64.b64encode(
            f"{self.username}:{self.password}".encode()).decode()
        status, data = json_call(
            self.endpoint, method,
            f"/_db/{urllib.parse.quote(self.database)}{path}", body=body,
            headers={"Authorization": f"Basic {token}"},
            timeout_s=self.timeout_s)
        return status, data if data is not None else {}

    @staticmethod
    def _key_of(arango_id: str) -> str:
        """``collection/key`` -> ``key`` (the embedded adapter's ids)."""
        return arango_id.rpartition("/")[2]

    # ----------------------------------------------------- native verbs
    def create_document(self, collection: str, document: dict) -> str:
        def op():
            status, data = self._call(
                "POST",
                f"/_api/document/{urllib.parse.quote(collection)}",
                body=document)
            if status not in (200, 201, 202):
                raise ArangoWireError(f"create -> {status}: {data}")
            return data["_key"]
        return self._observed("CREATE_DOC", collection, op)

    def get_document(self, collection: str, doc_id: str) -> dict:
        def op():
            status, data = self._call(
                "GET", f"/_api/document/{urllib.parse.quote(collection)}/"
                       f"{urllib.parse.quote(doc_id)}")
            if status == 404:
                raise NodeNotFound(f"{collection}/{doc_id}")
            if status != 200:
                raise ArangoWireError(f"get -> {status}: {data}")
            return {k: v for k, v in data.items()
                    if k not in ("_id", "_key", "_rev")}
        return self._observed("GET_DOC", collection, op)

    def update_document(self, collection: str, doc_id: str,
                        changes: dict) -> None:
        def op():
            status, data = self._call(
                "PATCH",
                f"/_api/document/{urllib.parse.quote(collection)}/"
                f"{urllib.parse.quote(doc_id)}", body=changes)
            if status == 404:
                raise NodeNotFound(f"{collection}/{doc_id}")
            if status not in (200, 201, 202):
                raise ArangoWireError(f"update -> {status}: {data}")
        self._observed("UPDATE_DOC", collection, op)

    def delete_document(self, collection: str, doc_id: str) -> None:
        def op():
            status, data = self._call(
                "DELETE",
                f"/_api/document/{urllib.parse.quote(collection)}/"
                f"{urllib.parse.quote(doc_id)}")
            if status == 404:
                raise NodeNotFound(f"{collection}/{doc_id}")
            if status not in (200, 202):
                raise ArangoWireError(f"delete -> {status}: {data}")
        self._observed("DELETE_DOC", collection, op)

    def create_edge_document(self, edge_collection: str, from_id: str,
                             to_id: str) -> None:
        # the embedded adapter takes bare keys; the wire format demands
        # collection/key — accept both
        if "/" not in from_id:
            from_id = f"v/{from_id}"
        if "/" not in to_id:
            to_id = f"v/{to_id}"

        def op():
            status, data = self._call(
                "POST",
                f"/_api/document/{urllib.parse.quote(edge_collection)}",
                body={"_from": from_id, "_to": to_id})
            if status not in (200, 201, 202):
                raise ArangoWireError(f"edge -> {status}: {data}")
        self._observed("CREATE_EDGE", edge_collection, op)

    def query(self, collection: str, flt: dict | None = None) -> list[dict]:
        def op():
            status, data = self._call(
                "PUT", "/_api/simple/by-example",
                body={"collection": collection, "example": flt or {}})
            if status != 201:
                raise ArangoWireError(f"query -> {status}: {data}")
            out = []
            for doc in data.get("result", []):
                row = {k: v for k, v in doc.items()
                       if k not in ("_id", "_key", "_rev")}
                row["_id"] = self._key_of(doc.get("_id", ""))
                out.append(row)
            return out
        return self._observed("QUERY", collection, op)

    def traversal(self, start_id: str, edge_collection: str,
                  depth: int = 1) -> list[dict]:
        def op():
            status, data = self._call(
                "POST", "/_api/traversal",
                body={"startVertex": start_id,
                      "edgeCollection": edge_collection,
                      "direction": "outbound", "maxDepth": depth})
            if status != 200:
                raise ArangoWireError(f"traversal -> {status}: {data}")
            out = []
            vertices = data.get("result", {}).get("visited", {}) \
                .get("vertices", [])
            for doc in vertices:
                row = {k: v for k, v in doc.items()
                       if k not in ("_id", "_key", "_rev")}
                row["_id"] = self._key_of(doc.get("_id", ""))
                out.append(row)
            return out
        return self._observed("TRAVERSAL", edge_collection, op)

    def health_check(self) -> dict[str, Any]:
        try:
            status, data = self._call("GET", "/_api/version")
            return {"status": "UP" if status == 200 else "DOWN",
                    "details": {"endpoint": self.endpoint,
                                "version": data.get("version", "")}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

class MiniArangoServer(ThreadedHTTPMiniServer):
    """The ArangoDB HTTP document surface over the embedded adapter."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 username: str = "root", password: str = "") -> None:
        super().__init__(host, port)
        self.username = username
        self.password = password
        self.store = ArangoDB(GraphEngine())

    def _authorized(self, request) -> bool:
        if not self.password:
            return True
        got = request.headers.get("authorization", "")
        want = base64.b64encode(
            f"{self.username}:{self.password}".encode()).decode()
        return got == f"Basic {want}"

    def handle(self, request) -> tuple[int, bytes, str]:
        if not self._authorized(request):
            return 401, b'{"error": true, "code": 401}', "application/json"
        try:
            return self._route(request)
        except NodeNotFound as exc:
            return 404, json.dumps(
                {"error": True, "code": 404,
                 "errorMessage": str(exc)}).encode(), "application/json"
        except GraphError as exc:
            return 400, json.dumps(
                {"error": True, "code": 400,
                 "errorMessage": str(exc)}).encode(), "application/json"

    def _route(self, request) -> tuple[int, bytes, str]:
        path = request.path
        # strip the /_db/{name} prefix real deployments use
        if path.startswith("/_db/"):
            path = "/" + path.split("/", 3)[3]
        if path == "/_api/version":
            return 200, b'{"server": "arango", "version": "3.11-mini"}', \
                "application/json"
        if path == "/_api/simple/by-example" and request.method == "PUT":
            body = json.loads(request.body)
            docs = self.store.query(body["collection"],
                                    body.get("example") or None)
            result = [dict(d, _id=f"{body['collection']}/{d['_id']}",
                           _key=d["_id"]) for d in docs]
            return 201, json.dumps(
                {"result": result, "count": len(result)}).encode(), \
                "application/json"
        if path == "/_api/traversal" and request.method == "POST":
            body = json.loads(request.body)
            docs = self.store.traversal(body["startVertex"],
                                        body["edgeCollection"],
                                        int(body.get("maxDepth", 1)))
            vertices = [dict(d, _id=f"v/{d['_id']}", _key=d["_id"])
                        for d in docs]
            return 200, json.dumps(
                {"result": {"visited": {"vertices": vertices,
                                        "paths": []}}}).encode(), \
                "application/json"
        if path.startswith("/_api/document/"):
            rest = path[len("/_api/document/"):]
            collection, _, key = rest.partition("/")
            if request.method == "POST":
                doc = json.loads(request.body)
                if "_from" in doc and "_to" in doc:
                    self.store.create_edge_document(
                        collection,
                        doc["_from"].rpartition("/")[2],
                        doc["_to"].rpartition("/")[2])
                    new_key = ""
                else:
                    new_key = self.store.create_document(collection, doc)
                return 201, json.dumps(
                    {"_id": f"{collection}/{new_key}",
                     "_key": new_key}).encode(), "application/json"
            if request.method == "GET":
                doc = self.store.get_document(collection, key)
                doc.update(_id=f"{collection}/{key}", _key=key)
                return 200, json.dumps(doc).encode(), "application/json"
            if request.method == "PATCH":
                self.store.update_document(collection, key,
                                           json.loads(request.body))
                return 200, json.dumps(
                    {"_id": f"{collection}/{key}",
                     "_key": key}).encode(), "application/json"
            if request.method == "DELETE":
                self.store.get_document(collection, key)  # 404 if absent
                self.store.delete_document(collection, key)
                return 200, json.dumps(
                    {"_id": f"{collection}/{key}"}).encode(), \
                    "application/json"
        return 400, b'{"error": true, "code": 400}', "application/json"
