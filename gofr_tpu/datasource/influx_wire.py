"""InfluxDB network client speaking the 1.x HTTP API (line protocol
writes, InfluxQL queries), plus a mini server.

The reference's InfluxDB module is a driver-backed network client
(container/datasources.go:797-839). This client speaks the database's
HTTP wire surface directly — ``POST /write?db=`` with line protocol,
``GET /query?q=`` returning the ``results/series`` JSON — behind the
same method surface as the embedded
:class:`~gofr_tpu.datasource.timeseries.InfluxDB` adapter, so swapping
is a constructor change. Buckets map to databases (the 1.x name for
the same concept).

:class:`MiniInfluxServer` implements the same HTTP surface over the
embedded :class:`~gofr_tpu.datasource.timeseries.SeriesEngine` on the
framework's own HTTP server — hermetic wire tests, real bytes.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import urllib.request
from typing import Any

from . import Instrumented
from .miniserver import ThreadedHTTPMiniServer
from .timeseries import SeriesEngine, TimeseriesError


# ----------------------------------------------------------- line protocol

def escape_tag(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace(",", "\\,") \
        .replace(" ", "\\ ").replace("=", "\\=")


def escape_measurement(value: str) -> str:
    # measurements only escape ',' and ' ' — '=' is literal here
    return str(value).replace("\\", "\\\\").replace(",", "\\,") \
        .replace(" ", "\\ ")


def encode_line(measurement: str, fields: dict[str, float],
                tags: dict | None = None, ts: float | None = None) -> str:
    """One line-protocol record: ``m,tag=v field=1.5 <ns>``."""
    if not fields:
        raise TimeseriesError("at least one field required")
    parts = [escape_measurement(measurement)]
    for key in sorted(tags or {}):
        parts.append(f"{escape_tag(key)}={escape_tag((tags or {})[key])}")
    head = ",".join(parts)
    body = ",".join(f"{escape_tag(k)}={float(v)}"
                    for k, v in sorted(fields.items()))
    line = f"{head} {body}"
    if ts is not None:
        line += f" {int(ts * 1e9)}"
    return line


#: placeholders for escaped separators so plain str.split works on the
#: unescaped ones, then tokens unescape individually
_ESCAPES = (("\\\\", "\x01"), ("\\ ", "\x02"), ("\\,", "\x03"),
            ("\\=", "\x04"))


def _unescape(token: str) -> str:
    for seq, mark in _ESCAPES:
        token = token.replace(mark, seq[1])
    return token


def decode_line(line: str) -> tuple[str, dict, dict, float | None]:
    """-> (measurement, tags, fields, ts_seconds|None)."""
    s = line.strip()
    for seq, mark in _ESCAPES:
        s = s.replace(seq, mark)
    chunks = [c for c in s.split(" ") if c]
    if len(chunks) < 2:
        raise TimeseriesError(f"bad line: {line!r}")
    head, field_part = chunks[0], chunks[1]
    ts = int(chunks[2]) / 1e9 if len(chunks) > 2 else None
    head_parts = head.split(",")
    measurement = _unescape(head_parts[0])
    tags = {}
    for tag in head_parts[1:]:
        k, _, v = tag.partition("=")
        tags[_unescape(k)] = _unescape(v)
    fields = {}
    for fv in field_part.split(","):
        k, _, v = fv.partition("=")
        fields[_unescape(k)] = float(v.rstrip("i"))
    return measurement, tags, fields, ts


# ----------------------------------------------------------------- client

class InfluxWire(Instrumented):
    """HTTP/line-protocol client with the embedded adapter's surface.
    Shares the embedded adapter's ``app_influxdb_stats`` series."""

    metric = "app_influxdb_stats"
    log_tag = "INFLUX"

    def __init__(self, *, url: str = "http://localhost:8086",
                 timeout_s: float = 10.0) -> None:
        if "://" not in url:
            url = "http://" + url
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to InfluxDB", url=self.url)

    def close(self) -> None:
        pass  # connections are per-request

    def _post(self, path: str, body: bytes,
              content_type: str = "text/plain") -> bytes:
        req = urllib.request.Request(
            self.url + path, data=body,
            headers={"Content-Type": content_type})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.read()
        except urllib.error.HTTPError as exc:
            raise TimeseriesError(
                f"{path} -> {exc.code}: {exc.read()[:200]!r}") from exc

    def _get(self, path: str) -> dict:
        try:
            with urllib.request.urlopen(self.url + path,
                                        timeout=self.timeout_s) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as exc:
            raise TimeseriesError(
                f"{path} -> {exc.code}: {exc.read()[:200]!r}") from exc

    # ----------------------------------------------------------- surface
    @staticmethod
    def _ident(name: str) -> str:
        """Double-quoted InfluxQL identifier; embedded '"' cannot be
        escaped portably, so reject it outright."""
        if '"' in name or "\n" in name:
            raise TimeseriesError(f"invalid identifier {name!r}")
        return f'"{name}"'

    @staticmethod
    def _quote_str(value: str) -> str:
        """Single-quoted InfluxQL string literal."""
        escaped = str(value).replace("\\", "\\\\").replace("'", "\\'")
        return f"'{escaped}'"

    def create_bucket(self, bucket: str) -> None:
        def op():
            q = urllib.parse.quote(f"CREATE DATABASE {self._ident(bucket)}")
            self._post(f"/query?q={q}", b"")
        self._observed("CREATE_BUCKET", bucket, op)

    def delete_bucket(self, bucket: str) -> None:
        def op():
            q = urllib.parse.quote(f"DROP DATABASE {self._ident(bucket)}")
            self._post(f"/query?q={q}", b"")
        self._observed("DELETE_BUCKET", bucket, op)

    def list_buckets(self) -> list[str]:
        out = self._get("/query?q=" + urllib.parse.quote("SHOW DATABASES"))
        series = out.get("results", [{}])[0].get("series", [{}])[0]
        return sorted(v[0] for v in series.get("values", []))

    def write_point(self, bucket: str, measurement: str, ts: float,
                    fields: dict[str, float],
                    tags: dict | None = None) -> None:
        def op():
            line = encode_line(measurement, fields, tags, ts)
            self._post(f"/write?db={urllib.parse.quote(bucket)}",
                       line.encode())
        self._observed("WRITE", f"{bucket}/{measurement}", op)

    def query(self, bucket: str, measurement: str, field: str,
              start: float | None = None, end: float | None = None,
              tags: dict | None = None) -> list[tuple[float, float]]:
        def op():
            conds = []
            if start is not None:
                conds.append(f"time >= {int(start * 1e9)}")
            if end is not None:
                conds.append(f"time <= {int(end * 1e9)}")
            for k, v in (tags or {}).items():
                conds.append(f"{self._ident(k)} = {self._quote_str(v)}")
            q = (f"SELECT {self._ident(field)} "
                 f"FROM {self._ident(measurement)}")
            if conds:
                q += " WHERE " + " AND ".join(conds)
            out = self._get(
                f"/query?db={urllib.parse.quote(bucket)}&epoch=ns&q="
                + urllib.parse.quote(q))
            result = out.get("results", [{}])[0]
            if "error" in result:
                raise TimeseriesError(result["error"])
            series = result.get("series") or [{}]
            return [(v[0] / 1e9, v[1])
                    for v in series[0].get("values", [])]
        return self._observed("QUERY", f"{bucket}/{measurement}", op)

    def aggregate(self, bucket: str, measurement: str, field: str,
                  aggregator: str = "avg", start: float | None = None,
                  end: float | None = None) -> float | None:
        fn = {"avg": "MEAN", "sum": "SUM", "min": "MIN", "max": "MAX",
              "count": "COUNT"}.get(aggregator)
        if fn is None:
            raise TimeseriesError(f"unknown aggregator {aggregator!r}")
        conds = []
        if start is not None:
            conds.append(f"time >= {int(start * 1e9)}")
        if end is not None:
            conds.append(f"time <= {int(end * 1e9)}")
        q = (f"SELECT {fn}({self._ident(field)}) "
             f"FROM {self._ident(measurement)}")
        if conds:
            q += " WHERE " + " AND ".join(conds)
        out = self._get(f"/query?db={urllib.parse.quote(bucket)}&q="
                        + urllib.parse.quote(q))
        series = out.get("results", [{}])[0].get("series")
        if not series or not series[0].get("values"):
            return None
        return series[0]["values"][0][1]

    def health_check(self) -> dict[str, Any]:
        try:
            self._get("/ping?verbose=true")
            return {"status": "UP", "details": {"url": self.url}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------ mini server

class MiniInfluxServer(ThreadedHTTPMiniServer):
    """The 1.x HTTP surface over the embedded SeriesEngine, on the
    framework's own HTTP server (lifecycle from
    :class:`~gofr_tpu.datasource.miniserver.ThreadedHTTPMiniServer`)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(host, port)
        self.engines: dict[str, SeriesEngine] = {}
        self._lock = threading.Lock()

    def _engine(self, db: str) -> SeriesEngine:
        with self._lock:
            if db not in self.engines:
                self.engines[db] = SeriesEngine()
            return self.engines[db]

    def handle(self, request) -> tuple[int, bytes, str]:
        try:
            status, payload = self._route(request)
        except TimeseriesError as exc:
            status, payload = 400, {"error": str(exc)}
        body = b"" if payload is None else json.dumps(payload).encode()
        return status, body, "application/json"

    def _route(self, request) -> tuple[int, Any]:
        if request.path == "/ping":
            return 200, {"version": "1.8-mini"}
        if request.path == "/write":
            db = request.param("db") or "default"
            engine = self._engine(db)
            for line in request.body.decode().splitlines():
                if not line.strip():
                    continue
                measurement, tags, fields, ts = decode_line(line)
                stamp = ts if ts is not None else time.time()
                for field, value in fields.items():
                    engine.put(f"{db}/{measurement}", stamp, value,
                               dict(tags, _field=field))
            return 204, None
        if request.path == "/query":
            return self._query(request)
        return 404, {"error": f"no route {request.path}"}

    def _query(self, request) -> tuple[int, Any]:
        q = request.param("q").strip()
        db = request.param("db") or "default"
        upper = q.upper()
        if upper.startswith("CREATE DATABASE"):
            self._engine(q.split('"')[1] if '"' in q else q.split()[-1])
            return 200, {"results": [{}]}
        if upper.startswith("DROP DATABASE"):
            name = q.split('"')[1] if '"' in q else q.split()[-1]
            with self._lock:
                self.engines.pop(name, None)
            return 200, {"results": [{}]}
        if upper.startswith("SHOW DATABASES"):
            with self._lock:
                names = sorted(self.engines)
            return 200, {"results": [{"series": [
                {"name": "databases", "columns": ["name"],
                 "values": [[n] for n in names]}]}]}
        if upper.startswith("SELECT"):
            return self._select(db, q)
        return 400, {"results": [{"error": f"unsupported query {q!r}"}]}

    _AGG = {"MEAN": "avg", "SUM": "sum", "MIN": "min", "MAX": "max",
            "COUNT": "count"}

    def _select(self, db: str, q: str) -> tuple[int, Any]:
        import re
        m = re.match(
            r'SELECT\s+(?:(\w+)\()?"([^"]+)"\)?\s+FROM\s+"([^"]+)"'
            r'(?:\s+WHERE\s+(.*))?$', q, re.IGNORECASE)
        if not m:
            return 400, {"results": [{"error": f"cannot parse {q!r}"}]}
        agg, field, measurement, where = m.groups()
        start = end = None
        tags = {"_field": field}
        for cond in (where or "").split(" AND "):
            cond = cond.strip()
            if not cond:
                continue
            tm = re.match(r"time\s*(>=|<=)\s*(\d+)", cond)
            if tm:
                ns = int(tm.group(2)) / 1e9
                if tm.group(1) == ">=":
                    start = ns
                else:
                    end = ns
                continue
            km = re.match(r'"([^"]+)"\s*=\s*\'((?:[^\'\\]|\\.)*)\'', cond)
            if km:
                tags[km.group(1)] = (km.group(2)
                                     .replace("\\'", "'")
                                     .replace("\\\\", "\\"))
        engine = self._engine(db)
        key = f"{db}/{measurement}"
        if agg:
            name = self._AGG.get(agg.upper())
            if name is None:
                return 400, {"results": [{"error": f"agg {agg}?"}]}
            value = engine.aggregate(key, name, start=start, end=end,
                                     tags=tags)
            if value is None:
                return 200, {"results": [{}]}
            return 200, {"results": [{"series": [
                {"name": measurement, "columns": ["time", name],
                 "values": [[0, value]]}]}]}
        points = engine.query(key, start, end, tags)
        return 200, {"results": [{"series": [
            {"name": measurement, "columns": ["time", field],
             "values": [[int(ts * 1e9), v] for ts, v, _ in points]}]}]}
