"""NATS-KV: the container's KV interface over JetStream buckets.

The analog of reference ``datasource/kv-store/nats`` (nats.go:43 — the
KV interface over ``nats.KeyValue``): a bucket is the JetStream stream
``KV_<bucket>`` capturing subjects ``$KV.<bucket>.>``;

- ``set`` publishes the value to ``$KV.<bucket>.<key>``,
- ``get`` is a direct ``$JS.API.STREAM.MSG.GET`` with ``last_by_subj``,
- ``delete`` publishes an empty message carrying the ``KV-Operation:
  DEL`` header — the tombstone real NATS clients write, so reads see
  deletion without the server compacting history first.

This speaks the same bytes as a real nats-server (the JetStream wire
client underneath), and works hermetically against
:class:`~gofr_tpu.pubsub.jetstream.MiniJetStreamServer`.  The sync
surface matches the repo's other KV backends (get/set/delete/health);
the asyncio wire client runs on a private background loop.
"""

from __future__ import annotations

import asyncio
import base64
import json
import threading
import time
from typing import Any

from ..pubsub.jetstream import JS_API, JetStreamClient, JetStreamError
from . import ProviderMixin
from .kv import KeyNotFound, KVError


class NATSKV(ProviderMixin):
    """KV store over a JetStream bucket (reference nats.go Client)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 4222, *,
                 bucket: str = "default", history: int = 1,
                 timeout_s: float = 5.0) -> None:
        if not bucket or any(c in ".*> " or ord(c) < 0x21
                             for c in bucket):
            raise KVError(f"invalid bucket name {bucket!r}")
        self.bucket = bucket
        self.history = history
        self.timeout_s = timeout_s
        self._client = JetStreamClient(host, port,
                                       request_timeout_s=timeout_s)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ plumbing
    def _run(self, coro):
        if self._loop is None:
            raise KVError("NATS-KV not connected")
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(self.timeout_s * 2)

    def _publish_checked(self, subject: str, payload: bytes,
                         headers: dict | None = None) -> None:
        """Publish into the bucket stream and insist on a PubAck — an
        error ack or status frame must not read as success."""
        async def go():
            ack = json.loads(await self._client._request(
                subject, payload, headers=headers) or b"{}")
            if "stream" not in ack:
                raise KVError(f"publish rejected for {subject}: {ack}")
        self._run(go())

    def _observed(self, op: str, key: str, fn):
        start = time.perf_counter()
        try:
            return fn()
        finally:
            elapsed = time.perf_counter() - start
            if self.logger is not None:
                self.logger.debug(
                    f"NATSKV {int(elapsed * 1e6):6d}µs {op} "
                    f"{self.bucket}/{key}")
            if self.metrics is not None:
                # reference histogram name (nats.go Connect); seconds,
                # like every other app_*_stats datasource histogram —
                # this write was both unregistered (silently dropped)
                # and in milliseconds until gofrlint's metric-hygiene
                # rule caught it
                self.metrics.record_histogram("app_nats_kv_stats",
                                              elapsed,
                                              type=op.lower())

    def _subject(self, key: str) -> str:
        # control chars (CR/LF!) would terminate the PUB control line
        # early — protocol injection, not just a bad key
        if not key or key.startswith(".") or key.endswith(".") \
                or any(c in "*>" or ord(c) < 0x21 for c in key):
            raise KVError(f"invalid key {key!r}")
        return f"$KV.{self.bucket}.{key}"

    # ------------------------------------------------------------- session
    def connect(self) -> None:
        if self._loop is not None:
            return
        loop = asyncio.new_event_loop()
        thread = threading.Thread(target=loop.run_forever,
                                  name="nats-kv", daemon=True)
        thread.start()
        self._loop, self._thread = loop, thread

        async def dial():
            await self._client.connect()
            # CreateKeyValue: per-subject history is the bucket's
            # version depth; 'exists' errors are fine on reconnect
            await self._client._api(
                f"{JS_API}.STREAM.CREATE.KV_{self.bucket}",
                {"name": f"KV_{self.bucket}",
                 "subjects": [f"$KV.{self.bucket}.>"],
                 "max_msgs_per_subject": self.history,
                 "allow_rollup_hdrs": True, "deny_delete": True,
                 "storage": "memory"})
        try:
            self._run(dial())
        except Exception:
            self.close()
            raise

    def close(self) -> None:
        loop, self._loop = self._loop, None
        if loop is not None:
            try:
                asyncio.run_coroutine_threadsafe(
                    self._client.close(), loop).result(self.timeout_s)
            except Exception:
                pass
            loop.call_soon_threadsafe(loop.stop)
            if self._thread is not None:
                self._thread.join(self.timeout_s)
            self._thread = None
            loop.close()  # release the selector/self-pipe fds

    # ----------------------------------------------------------------- ops
    def get(self, key: str) -> str:
        subject = self._subject(key)

        def op():
            async def go():
                return await self._client._api(
                    f"{JS_API}.STREAM.MSG.GET.KV_{self.bucket}",
                    {"last_by_subj": subject})
            try:
                body = self._run(go())
            except JetStreamError as exc:
                if "404" in str(exc) or "no message" in str(exc):
                    raise KeyNotFound(key) from exc
                raise
            msg = body.get("message")
            if not isinstance(msg, dict):
                # e.g. an empty 503 no-responders status frame parsed
                # as {} — that is an outage, not an empty value
                raise KVError(f"malformed MSG.GET reply for {subject}: "
                              f"{body}")
            hdrs = base64.b64decode(msg.get("hdrs", "")).decode(
                "latin-1") if msg.get("hdrs") else ""
            for line in hdrs.splitlines():
                if line.lower().startswith("kv-operation:") \
                        and line.split(":", 1)[1].strip() in ("DEL", "PURGE"):
                    raise KeyNotFound(key)
            return base64.b64decode(msg.get("data", "")).decode()
        return self._observed("GET", key, op)

    def set(self, key: str, value: str) -> None:
        subject = self._subject(key)
        payload = value.encode() if isinstance(value, str) else bytes(value)
        return self._observed(
            "SET", key, lambda: self._publish_checked(subject, payload))

    def delete(self, key: str) -> None:
        subject = self._subject(key)
        return self._observed(
            "DELETE", key, lambda: self._publish_checked(
                subject, b"", headers={"KV-Operation": "DEL"}))

    # -------------------------------------------------------------- health
    def health_check(self) -> dict[str, Any]:
        out = self._client.health_check()
        if self._loop is None:
            out["status"] = "DOWN"
        out["backend"] = "nats-kv"
        out.setdefault("details", {})["bucket"] = self.bucket
        return out
