"""Datasource layer: instrumented stores behind narrow interfaces.

Mirrors the reference's datasource tree (pkg/gofr/datasource/*): every
store follows the provider pattern — ``use_logger`` / ``use_metrics`` /
``use_tracer`` then ``connect`` (reference container/datasources.go:346-358)
— and exposes ``health_check`` for the container's aggregate health
(container/health.go:8-98).

Shipped backends:
- :mod:`.sql` — sqlite-backed SQL with dialect-aware placeholders,
  query logging, metrics, ORM-lite ``select``.
- :mod:`.redis` — Redis-shaped KV with an in-process backend (the
  miniredis analog SURVEY §4 prescribes for hermetic tests).
- :mod:`.kv` — minimal key-value store interface (badger analog) with
  in-memory and sqlite-file backends.
- :mod:`.file_store` — FileSystem abstraction over the local FS with
  JSON/CSV row readers.
- :mod:`.dbresolver` — SQL primary/replica router with per-replica
  circuit breakers.
- :mod:`.document` — document-store family (Mongo/Elasticsearch/Solr/
  Couchbase-shaped) over one embedded engine.
- :mod:`.columnar` — CQL/columnar family (Cassandra/ScyllaDB/
  Clickhouse/Oracle-shaped) over sqlite.
- :mod:`.graph` — graph family (Dgraph/ArangoDB/SurrealDB-shaped).
- :mod:`.timeseries` — time-series family (OpenTSDB/InfluxDB-shaped).

Network wire clients (each speaks its store's real protocol and ships
a protocol-faithful mini server for hermetic tests; swapping embedded
for network is a constructor change): :mod:`.redis_wire` (RESP2),
:mod:`.postgres_wire` (v3 protocol + SCRAM-SHA-256),
:mod:`.mysql_wire` (v10 handshake + native-password auth + COM_QUERY),
:mod:`.cassandra_wire` (CQL native protocol v4, incl. ``ScyllaWire``),
:mod:`.couchbase_wire` (memcached binary KV + N1QL HTTP),
:mod:`.mongo_wire` (OP_MSG), :mod:`.s3_wire` (SigV4),
:mod:`.gcs_wire` (JSON API), :mod:`.azure_blob_wire` (SharedKey),
:mod:`.es_wire`, :mod:`.solr_wire`, :mod:`.clickhouse_wire` (HTTP
interface), :mod:`.influx_wire`, :mod:`.opentsdb_wire`,
:mod:`.arango_wire`, :mod:`.dgraph_wire` (generated DQL),
:mod:`.surreal_wire` (WebSocket JSON-RPC), :mod:`.dynamo_wire`
(DynamoDB JSON 1.0 + SigV4), :mod:`.oracle_wire` (TNS transport +
O5LOGON-style auth), :mod:`.nats_kv` (KV over JetStream buckets),
:mod:`.ftp` (FTP), and
:mod:`.sftp_wire` — SFTP v3 over :mod:`.ssh_transport`, an SSH2
transport implemented from the RFCs (curve25519-sha256 kex,
ssh-ed25519 host keys, aes128-ctr, hmac-sha2-256, password auth).
"""

import time
from typing import Any, Protocol


class HealthChecker(Protocol):
    """reference container/datasources.go:360-364."""

    def health_check(self) -> dict[str, Any]: ...


class Provider(Protocol):
    """reference container/datasources.go:346-358."""

    def use_logger(self, logger: Any) -> None: ...

    def use_metrics(self, metrics: Any) -> None: ...

    def use_tracer(self, tracer: Any) -> None: ...

    def connect(self) -> None: ...


class ProviderMixin:
    """The use_logger/use_metrics/use_tracer wiring every store shares."""

    logger: Any = None
    metrics: Any = None
    tracer: Any = None

    def use_logger(self, logger: Any) -> None:
        self.logger = logger

    def use_metrics(self, metrics: Any) -> None:
        self.metrics = metrics

    def use_tracer(self, tracer: Any) -> None:
        self.tracer = tracer


# 50µs–30s, the reference's datasource latency buckets
# (container/container.go:339-344)
DATASOURCE_BUCKETS = (0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01,
                      0.05, 0.1, 0.5, 1, 5, 30)


class Instrumented(ProviderMixin):
    """Provider + per-op observation: every operation logs a one-line
    QueryLog and records into the store's latency histogram, the way
    every reference datasource does (e.g. sql/db.go:47-60)."""

    #: metric name; subclasses override (registered lazily if missing)
    metric = "app_datasource_stats"
    #: short tag used in the log line ("MONGO", "CQL", ...)
    log_tag = "DS"

    def _observed(self, op: str, detail: str, fn):
        start = time.perf_counter()
        try:
            return fn()
        finally:
            micros = int((time.perf_counter() - start) * 1e6)
            if self.logger is not None:
                self.logger.debug(
                    f"{self.log_tag} {micros:6d}µs {op} {detail}")
            if self.metrics is not None:
                if self.metrics.get(self.metric) is None:
                    # concurrent first ops may race to register; the
                    # loser's MetricsError must not clobber fn's result
                    try:
                        self.metrics.new_histogram(  # gofrlint: allow(metric-hygiene) -- per-datasource name (app_<ds>_stats) is instance config; registered right here before the only write
                            self.metric,
                            f"{self.log_tag} op time in seconds",
                            buckets=DATASOURCE_BUCKETS)
                    except Exception:
                        pass
                self.metrics.record_histogram(self.metric, micros / 1e6,  # gofrlint: allow(metric-hygiene) -- same dynamic per-datasource name, registered four lines up
                                              type=op.lower())
