"""Datasource layer: instrumented stores behind narrow interfaces.

Mirrors the reference's datasource tree (pkg/gofr/datasource/*): every
store follows the provider pattern — ``use_logger`` / ``use_metrics`` /
``use_tracer`` then ``connect`` (reference container/datasources.go:346-358)
— and exposes ``health_check`` for the container's aggregate health
(container/health.go:8-98).

Shipped backends:
- :mod:`.sql` — sqlite-backed SQL with dialect-aware placeholders,
  query logging, metrics, ORM-lite ``select``.
- :mod:`.redis` — Redis-shaped KV with an in-process backend (the
  miniredis analog SURVEY §4 prescribes for hermetic tests).
- :mod:`.kv` — minimal key-value store interface (badger analog) with
  in-memory and sqlite-file backends.
- :mod:`.file_store` — FileSystem abstraction over the local FS with
  JSON/CSV row readers.
- :mod:`.dbresolver` — SQL primary/replica router with per-replica
  circuit breakers.
"""

from typing import Any, Protocol


class HealthChecker(Protocol):
    """reference container/datasources.go:360-364."""

    def health_check(self) -> dict[str, Any]: ...


class Provider(Protocol):
    """reference container/datasources.go:346-358."""

    def use_logger(self, logger: Any) -> None: ...

    def use_metrics(self, metrics: Any) -> None: ...

    def use_tracer(self, tracer: Any) -> None: ...

    def connect(self) -> None: ...


class ProviderMixin:
    """The use_logger/use_metrics/use_tracer wiring every store shares."""

    logger: Any = None
    metrics: Any = None
    tracer: Any = None

    def use_logger(self, logger: Any) -> None:
        self.logger = logger

    def use_metrics(self, metrics: Any) -> None:
        self.metrics = metrics

    def use_tracer(self, tracer: Any) -> None:
        self.tracer = tracer
