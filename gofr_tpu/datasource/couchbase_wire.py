"""Couchbase network client speaking the memcached binary protocol for
KV and the N1QL query service over HTTP, plus a mini server for both.

The reference's Couchbase module is a driver-backed network client
(container/datasources.go:748-788 over gocb). Couchbase's data plane
is the memcached binary protocol (24-byte header frames; GET/SET/ADD/
DELETE opcodes, SASL PLAIN auth, SELECT_BUCKET) and its query plane is
the N1QL REST service — this client implements both from the
specification. ``query`` generates real N1QL
(``SELECT d.* FROM `bucket` d WHERE d.`k` = $k``) with named
arguments. The method surface mirrors the embedded
:class:`~gofr_tpu.datasource.document.Couchbase` adapter
(get/upsert/insert/remove/query).

:class:`MiniCouchbaseServer` runs the binary-protocol TCP listener and
the query-service HTTP listener over one embedded adapter — verified
SASL PLAIN, real frames, one shared dataset across both planes.
"""

from __future__ import annotations

import json
import re
import socket
import socketserver
import struct
import threading
from typing import Any

from . import Instrumented
from ._http import json_call
from .document import Couchbase, DocumentEngine, DocumentError, \
    DocumentNotFound
from .miniserver import ThreadedHTTPMiniServer

MAGIC_REQUEST = 0x80
MAGIC_RESPONSE = 0x81

OP_GET = 0x00
OP_SET = 0x01
OP_ADD = 0x02
OP_DELETE = 0x04
OP_SASL_LIST = 0x20
OP_SASL_AUTH = 0x21
OP_SELECT_BUCKET = 0x89

STATUS_OK = 0x0000
STATUS_NOT_FOUND = 0x0001
STATUS_EXISTS = 0x0002
STATUS_AUTH_ERROR = 0x0020


class CouchbaseWireError(DocumentError):
    """Non-OK binary status or query-service error."""

    def __init__(self, message: str, status: int = 0) -> None:
        super().__init__(message)
        self.status = status


def pack_frame(magic: int, opcode: int, key: bytes = b"",
               extras: bytes = b"", value: bytes = b"",
               status: int = 0, opaque: int = 0, cas: int = 0) -> bytes:
    total = len(extras) + len(key) + len(value)
    header = struct.pack("!BBHBBHIIQ", magic, opcode, len(key),
                         len(extras), 0, status, total, opaque, cas)
    return header + extras + key + value


class _BinarySocket:
    """Framed read/write of memcached binary packets."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._buf = b""

    def _exactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise CouchbaseWireError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def send(self, frame: bytes) -> None:
        self._sock.sendall(frame)

    def recv(self) -> tuple[int, int, bytes, bytes, bytes]:
        """-> (opcode, status, extras, key, value)."""
        header = self._exactly(24)
        (_magic, opcode, key_len, extras_len, _dt, status, total,
         _opaque, _cas) = struct.unpack("!BBHBBHIIQ", header)
        body = self._exactly(total)
        extras = body[:extras_len]
        key = body[extras_len:extras_len + key_len]
        value = body[extras_len + key_len:]
        return opcode, status, extras, key, value


class CouchbaseWire(Instrumented):
    """Binary-protocol KV + N1QL-over-HTTP client with the embedded
    adapter's verbs."""

    metric = "app_couchbase_stats"
    log_tag = "CB"

    def __init__(self, *, host: str = "localhost", kv_port: int = 11210,
                 query_endpoint: str = "http://localhost:8093",
                 username: str = "", password: str = "",
                 timeout_s: float = 30.0) -> None:
        self.host = host
        self.kv_port = kv_port
        if "://" not in query_endpoint:
            query_endpoint = "http://" + query_endpoint
        self.query_endpoint = query_endpoint.rstrip("/")
        self.username = username
        self.password = password
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._frames: _BinarySocket | None = None
        self._bucket = ""
        self._lock = threading.RLock()

    # ------------------------------------------------------------ connect
    def connect(self) -> None:
        if self._sock is not None:
            self.close()
        sock = socket.create_connection((self.host, self.kv_port),
                                        timeout=self.timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._frames = _BinarySocket(sock)
        try:
            if self.username:
                token = (b"\x00" + self.username.encode()
                         + b"\x00" + self.password.encode())
                _, status, _, _, value = self._round(
                    OP_SASL_AUTH, key=b"PLAIN", value=token)
                if status != STATUS_OK:
                    raise CouchbaseWireError(
                        f"SASL auth failed: {value.decode('utf-8', 'replace')}",
                        status=status)
        except BaseException:
            sock.close()
            self._sock = None
            self._frames = None
            raise
        if self.logger is not None:
            self.logger.info("connected to couchbase", host=self.host,
                             kv_port=self.kv_port)

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None
            self._frames = None
            self._bucket = ""

    def _round(self, opcode: int, key: bytes = b"", extras: bytes = b"",
               value: bytes = b"") -> tuple[int, int, bytes, bytes, bytes]:
        if self._frames is None:
            raise CouchbaseWireError("not connected; call connect() first")
        with self._lock:
            try:
                self._frames.send(pack_frame(MAGIC_REQUEST, opcode, key,
                                             extras, value))
                return self._frames.recv()
            except (OSError, TimeoutError) as exc:
                self.close()  # partial frame poisons the stream
                raise CouchbaseWireError(
                    f"connection lost mid-request ({exc})") from exc

    def _select_bucket(self, bucket: str) -> None:
        if bucket == self._bucket:
            return
        _, status, _, _, _ = self._round(OP_SELECT_BUCKET,
                                         key=bucket.encode())
        if status != STATUS_OK:
            raise CouchbaseWireError(f"select bucket {bucket!r} failed",
                                     status=status)
        self._bucket = bucket

    # ----------------------------------------------------- native verbs
    def get(self, bucket: str, key: str) -> dict:
        def op():
            # one lock scope for select+op: another thread's bucket
            # switch must not land between them (server-side bucket
            # state is per-connection)
            with self._lock:
                self._select_bucket(bucket)
                _, status, _, _, value = self._round(OP_GET,
                                                     key=key.encode())
            if status == STATUS_NOT_FOUND:
                raise DocumentNotFound(f"{bucket}/{key}")
            if status != STATUS_OK:
                raise CouchbaseWireError(f"get -> {status:#06x}",
                                         status=status)
            return json.loads(value)
        return self._observed("GET", bucket, op)

    def _store(self, opcode: int, bucket: str, key: str,
               document: dict) -> int:
        with self._lock:  # select+op atomically, see get()
            self._select_bucket(bucket)
            extras = struct.pack("!II", 0, 0)  # flags, expiry
            _, status, _, _, _ = self._round(
                opcode, key=key.encode(), extras=extras,
                value=json.dumps(document).encode())
        return status

    def upsert(self, bucket: str, key: str, document: dict) -> None:
        def op():
            status = self._store(OP_SET, bucket, key, document)
            if status != STATUS_OK:
                raise CouchbaseWireError(f"upsert -> {status:#06x}",
                                         status=status)
        self._observed("UPSERT", bucket, op)

    def insert(self, bucket: str, key: str, document: dict) -> None:
        def op():
            status = self._store(OP_ADD, bucket, key, document)
            if status == STATUS_EXISTS:
                raise DocumentError(f"duplicate id {key!r} in {bucket}")
            if status != STATUS_OK:
                raise CouchbaseWireError(f"insert -> {status:#06x}",
                                         status=status)
        self._observed("INSERT", bucket, op)

    def remove(self, bucket: str, key: str) -> None:
        def op():
            with self._lock:  # select+op atomically, see get()
                self._select_bucket(bucket)
                _, status, _, _, _ = self._round(OP_DELETE,
                                                 key=key.encode())
            if status == STATUS_NOT_FOUND:
                raise DocumentNotFound(f"{bucket}/{key}")
            if status != STATUS_OK:
                raise CouchbaseWireError(f"remove -> {status:#06x}",
                                         status=status)
        self._observed("REMOVE", bucket, op)

    def query(self, bucket: str, flt: dict | None = None) -> list[dict]:
        """Generates real N1QL with named arguments, POSTed to the
        query service (the gocb Cluster.Query path)."""
        def op():
            # identifiers ride in the statement text: validate them;
            # values are always parameterized
            if not re.fullmatch(r"[\w.-]+", bucket):
                raise CouchbaseWireError(f"invalid bucket name {bucket!r}")
            statement = f"SELECT d.* FROM `{bucket}` d"
            args: dict[str, Any] = {}
            for i, (key, value) in enumerate(sorted((flt or {}).items())):
                if not re.fullmatch(r"\w+", str(key)):
                    raise CouchbaseWireError(
                        f"invalid field name {key!r}")
                statement += (" WHERE" if i == 0 else " AND") \
                    + f" d.`{key}` = $p{i}"
                args[f"p{i}"] = value
            body = {"statement": statement, **{f"${k}": v
                                               for k, v in args.items()}}
            status, data = json_call(self.query_endpoint, "POST",
                                     "/query/service", body=body,
                                     timeout_s=self.timeout_s)
            if status != 200 or (isinstance(data, dict)
                                 and data.get("status") != "success"):
                raise CouchbaseWireError(f"query -> {status}: {data}")
            return data.get("results", [])
        return self._observed("QUERY", bucket, op)

    def health_check(self) -> dict[str, Any]:
        try:
            _, status, _, _, value = self._round(OP_SASL_LIST)
            return {"status": "UP" if status == STATUS_OK else "DOWN",
                    "details": {"host": self.host, "kv_port": self.kv_port,
                                "mechs": value.decode("utf-8", "replace")}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------ mini server

class _KVHandler(socketserver.BaseRequestHandler):
    @property
    def mini(self) -> "MiniCouchbaseServer":
        return self.server.mini  # type: ignore[attr-defined]

    def handle(self) -> None:
        frames = _BinarySocket(self.request)
        authed = not self.mini.password
        bucket = ""
        try:
            while True:
                opcode, _, extras, key, value = frames.recv()

                def reply(status: int = STATUS_OK, *, out: bytes = b"",
                          rx: bytes = b"") -> None:
                    frames.send(pack_frame(MAGIC_RESPONSE, opcode,
                                           extras=rx, value=out,
                                           status=status))

                if opcode == OP_SASL_LIST:
                    reply(out=b"PLAIN")
                elif opcode == OP_SASL_AUTH:
                    parts = value.split(b"\x00")
                    ok = (key == b"PLAIN" and len(parts) == 3
                          and parts[1].decode() == self.mini.username
                          and parts[2].decode() == self.mini.password)
                    authed = authed or ok
                    reply(STATUS_OK if ok else STATUS_AUTH_ERROR,
                          out=b"" if ok else b"Auth failure")
                elif not authed:
                    reply(STATUS_AUTH_ERROR, out=b"not authenticated")
                elif opcode == OP_SELECT_BUCKET:
                    bucket = key.decode()
                    reply()
                elif opcode == OP_GET:
                    try:
                        doc = self.mini.store.get(bucket, key.decode())
                    except DocumentNotFound:
                        reply(STATUS_NOT_FOUND, out=b"Not found")
                        continue
                    doc = {k: v for k, v in doc.items() if k != "_id"}
                    reply(out=json.dumps(doc).encode(),
                          rx=struct.pack("!I", 0))
                elif opcode in (OP_SET, OP_ADD):
                    doc = json.loads(value)
                    if opcode == OP_ADD:
                        try:
                            self.mini.store.insert(bucket, key.decode(),
                                                   doc)
                        except DocumentError:
                            reply(STATUS_EXISTS, out=b"Exists")
                            continue
                    else:
                        self.mini.store.upsert(bucket, key.decode(), doc)
                    reply()
                elif opcode == OP_DELETE:
                    try:
                        self.mini.store.remove(bucket, key.decode())
                    except DocumentNotFound:
                        reply(STATUS_NOT_FOUND, out=b"Not found")
                        continue
                    reply()
                else:
                    reply(0x0081, out=b"unknown command")
        except (CouchbaseWireError, ConnectionError, OSError):
            return


_N1QL_RE = re.compile(
    r"SELECT d\.\* FROM `(?P<bucket>[^`]+)` d"
    r"(?P<where>( (?:WHERE|AND) d\.`\w+` = \$\w+)*)$")


class _QueryServer(ThreadedHTTPMiniServer):
    def __init__(self, mini: "MiniCouchbaseServer") -> None:
        super().__init__()
        self.mini = mini

    def handle(self, request) -> tuple[int, bytes, str]:
        if request.path != "/query/service" or request.method != "POST":
            return 404, b'{"status": "fatal"}', "application/json"
        body = json.loads(request.body)
        match = _N1QL_RE.match(body.get("statement", "").strip())
        if not match:
            return 400, json.dumps(
                {"status": "fatal",
                 "errors": [{"msg": "unsupported N1QL"}]}).encode(), \
                "application/json"
        flt = {}
        for cond in re.finditer(r"d\.`(\w+)` = \$(\w+)",
                                match.group("where")):
            field, var = cond.groups()
            if f"${var}" not in body:
                return 400, json.dumps(
                    {"status": "fatal",
                     "errors": [{"msg": f"unbound ${var}"}]}).encode(), \
                    "application/json"
            flt[field] = body[f"${var}"]
        rows = self.mini.store.query(match.group("bucket"), flt or None)
        rows = [{k: v for k, v in r.items() if k != "_id"} for r in rows]
        return 200, json.dumps(
            {"status": "success", "results": rows}).encode(), \
            "application/json"


class MiniCouchbaseServer:
    """Binary-protocol KV listener + N1QL query-service listener over
    one embedded adapter. SASL PLAIN is verified when a password is
    configured."""

    def __init__(self, host: str = "127.0.0.1", *, username: str = "",
                 password: str = "") -> None:
        self.host = host
        self.username = username
        self.password = password
        self.store = Couchbase(DocumentEngine())
        self.kv_port = 0
        self.query_port = 0
        self._server: socketserver.ThreadingTCPServer | None = None
        self._thread: threading.Thread | None = None
        self._query = _QueryServer(self)

    def start(self) -> None:
        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = TCP((self.host, 0), _KVHandler)
        self._server.mini = self  # the handler reads this back
        self.kv_port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="mini-couchbase")
        self._thread.start()
        self._query.start()
        self.query_port = self._query.port

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self._query.close()
