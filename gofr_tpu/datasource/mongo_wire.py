"""MongoDB wire-protocol client: BSON + OP_MSG from first principles,
plus a mini server.

The reference's Mongo module is a driver-backed network client
(container/datasources.go:232 declares the interface;
datasource/mongo implements it over mongo-go-driver). This is that
client for real network deployments: BSON encoding/decoding and the
modern OP_MSG framing (opcode 2013, the only op modern servers speak)
written directly on a TCP socket — no driver dependency — behind the
same command surface as the embedded
:class:`~gofr_tpu.datasource.document.Mongo` adapter, so swapping is a
constructor change.

Commands speak the standard database-command documents: ``insert``,
``find`` (cursor firstBatch), ``update`` (``$set``), ``delete``,
``count``, ``drop``, ``ping``.

:class:`MiniMongoServer` is the hermetic stand-in: a threaded OP_MSG
server delegating semantics to the embedded
:class:`~gofr_tpu.datasource.document.DocumentEngine`, so wire-client
tests exercise real BSON bytes over a real socket.
"""

from __future__ import annotations

import datetime as _dt
import os
import socket
import struct
import threading
import time
from typing import Any

from . import Instrumented
from .document import DocumentEngine

OP_MSG = 2013


class MongoWireError(Exception):
    pass


# ------------------------------------------------------------------ BSON

def _cstring(s: str) -> bytes:
    return s.encode() + b"\x00"


class ObjectId:
    """12-byte Mongo object id (4B time, 5B random, 3B counter)."""

    _counter = int.from_bytes(os.urandom(3), "big")
    _random = os.urandom(5)
    _lock = threading.Lock()

    def __init__(self, raw: bytes | None = None) -> None:
        if raw is None:
            with ObjectId._lock:
                ObjectId._counter = (ObjectId._counter + 1) % (1 << 24)
                counter = ObjectId._counter
            raw = (struct.pack(">I", int(time.time())) + ObjectId._random
                   + counter.to_bytes(3, "big"))
        if len(raw) != 12:
            raise MongoWireError("ObjectId must be 12 bytes")
        self.raw = raw

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectId) and self.raw == other.raw

    def __hash__(self) -> int:
        return hash(self.raw)

    def __str__(self) -> str:
        return self.raw.hex()

    def __repr__(self) -> str:
        return f"ObjectId('{self.raw.hex()}')"


def encode_bson(doc: dict) -> bytes:
    out = bytearray()
    for key, value in doc.items():
        out += _encode_element(str(key), value)
    return struct.pack("<i", len(out) + 5) + bytes(out) + b"\x00"


def _encode_element(key: str, value: Any) -> bytes:
    name = _cstring(key)
    if isinstance(value, bool):          # before int: bool is int's child
        return b"\x08" + name + (b"\x01" if value else b"\x00")
    if isinstance(value, float):
        return b"\x01" + name + struct.pack("<d", value)
    if isinstance(value, str):
        raw = value.encode()
        return b"\x02" + name + struct.pack("<i", len(raw) + 1) + raw + b"\x00"
    if isinstance(value, dict):
        return b"\x03" + name + encode_bson(value)
    if isinstance(value, (list, tuple)):
        return b"\x04" + name + encode_bson(
            {str(i): v for i, v in enumerate(value)})
    if isinstance(value, bytes):
        return (b"\x05" + name + struct.pack("<i", len(value)) + b"\x00"
                + value)
    if isinstance(value, ObjectId):
        return b"\x07" + name + value.raw
    if isinstance(value, _dt.datetime):
        ms = int(value.timestamp() * 1000)
        return b"\x09" + name + struct.pack("<q", ms)
    if value is None:
        return b"\x0a" + name
    if isinstance(value, int):
        if -(1 << 31) <= value < (1 << 31):
            return b"\x10" + name + struct.pack("<i", value)
        return b"\x12" + name + struct.pack("<q", value)
    raise MongoWireError(f"cannot BSON-encode {type(value).__name__}")


def decode_bson(data: bytes, pos: int = 0) -> tuple[dict, int]:
    """-> (document, next position)."""
    size = struct.unpack_from("<i", data, pos)[0]
    end = pos + size - 1               # final 0x00
    pos += 4
    doc: dict = {}
    while pos < end:
        etype = data[pos]
        pos += 1
        nul = data.index(b"\x00", pos)
        key = data[pos:nul].decode()
        pos = nul + 1
        if etype == 0x01:
            doc[key] = struct.unpack_from("<d", data, pos)[0]
            pos += 8
        elif etype == 0x02:
            n = struct.unpack_from("<i", data, pos)[0]
            doc[key] = data[pos + 4:pos + 4 + n - 1].decode()
            pos += 4 + n
        elif etype == 0x03:
            doc[key], pos = decode_bson(data, pos)
        elif etype == 0x04:
            sub, pos = decode_bson(data, pos)
            doc[key] = [sub[k] for k in sorted(sub, key=int)]
        elif etype == 0x05:
            n = struct.unpack_from("<i", data, pos)[0]
            doc[key] = data[pos + 5:pos + 5 + n]
            pos += 5 + n
        elif etype == 0x07:
            doc[key] = ObjectId(data[pos:pos + 12])
            pos += 12
        elif etype == 0x08:
            doc[key] = data[pos] == 1
            pos += 1
        elif etype == 0x09:
            ms = struct.unpack_from("<q", data, pos)[0]
            doc[key] = _dt.datetime.fromtimestamp(
                ms / 1000, tz=_dt.timezone.utc)
            pos += 8
        elif etype == 0x0A:
            doc[key] = None
        elif etype == 0x10:
            doc[key] = struct.unpack_from("<i", data, pos)[0]
            pos += 4
        elif etype == 0x12:
            doc[key] = struct.unpack_from("<q", data, pos)[0]
            pos += 8
        else:
            raise MongoWireError(f"unsupported BSON type 0x{etype:02x}")
    return doc, end + 1


# ---------------------------------------------------------------- OP_MSG

def encode_op_msg(request_id: int, body: dict,
                  response_to: int = 0) -> bytes:
    payload = struct.pack("<I", 0) + b"\x00" + encode_bson(body)
    header = struct.pack("<iiii", 16 + len(payload), request_id,
                         response_to, OP_MSG)
    return header + payload


def decode_op_msg(frame: bytes) -> tuple[int, int, dict]:
    """Full frame (incl. header) -> (request_id, response_to, body)."""
    _length, request_id, response_to, opcode = struct.unpack_from(
        "<iiii", frame, 0)
    if opcode != OP_MSG:
        raise MongoWireError(f"unsupported opcode {opcode}")
    # flagBits (4) + section kind byte (1)
    if frame[20] != 0:
        raise MongoWireError("only kind-0 sections supported")
    body, _ = decode_bson(frame, 21)
    return request_id, response_to, body


def _read_frame(sock: socket.socket, buf: bytearray) -> bytes | None:
    while len(buf) < 4:
        chunk = sock.recv(65536)
        if not chunk:
            return None
        buf += chunk
    size = struct.unpack_from("<i", buf, 0)[0]
    while len(buf) < size:
        chunk = sock.recv(65536)
        if not chunk:
            return None
        buf += chunk
    frame = bytes(buf[:size])
    del buf[:size]
    return frame


# ----------------------------------------------------------------- client

class MongoWire(Instrumented):
    """Network Mongo client with the embedded adapter's surface.
    Shares the embedded adapter's metric series (``app_mongo_stats``,
    ``type=<command>``) so swapping engines never renames a series."""

    metric = "app_mongo_stats"
    log_tag = "MONGO"

    def __init__(self, *, host: str = "localhost", port: int = 27017,
                 database: str = "gofr", timeout_s: float = 10.0) -> None:
        self.host, self.port, self.database = host, port, database
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._buf = bytearray()
        self._req_ids = iter(range(1, 1 << 31))
        self._lock = threading.RLock()

    def connect(self) -> None:
        with self._lock:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self.logger is not None:
            self.logger.info("connected to Mongo",
                             addr=f"{self.host}:{self.port}")

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
            self._sock = None
            self._buf.clear()

    def command(self, body: dict) -> dict:
        """One OP_MSG round-trip; raises on {ok: 0} replies AND on
        per-document writeErrors (real servers report failed writes
        with ok: 1 + writeErrors — swallowing them is silent data
        loss)."""
        label = next(iter(body), "?")

        def op() -> dict:
            with self._lock:
                if self._sock is None:
                    self.connect()
                assert self._sock is not None
                full = {**body, "$db": self.database}
                try:
                    self._sock.sendall(
                        encode_op_msg(next(self._req_ids), full))
                    frame = _read_frame(self._sock, self._buf)
                except OSError:
                    self.close()
                    raise
                if frame is None:
                    self.close()
                    raise MongoWireError("connection closed")
                _, _, reply = decode_op_msg(frame)
            if not reply.get("ok"):
                raise MongoWireError(
                    str(reply.get("errmsg", "command failed")))
            if reply.get("writeErrors"):
                raise MongoWireError(str(reply["writeErrors"]))
            return reply
        return self._observed(label, self.database, op)

    # -------------------------------------------------- command surface
    def insert_one(self, collection: str, document: dict) -> Any:
        doc = dict(document)
        doc.setdefault("_id", ObjectId())
        self.command({"insert": collection, "documents": [doc]})
        return doc["_id"]

    def insert_many(self, collection: str, documents: Any) -> list:
        docs = [dict(d) for d in documents]
        for d in docs:
            d.setdefault("_id", ObjectId())
        self.command({"insert": collection, "documents": docs})
        return [d["_id"] for d in docs]

    def find(self, collection: str, flt: dict | None = None,
             limit: int | None = None) -> list[dict]:
        body: dict = {"find": collection, "filter": flt or {}}
        if limit:
            body["limit"] = int(limit)
        reply = self.command(body)
        return reply.get("cursor", {}).get("firstBatch", [])

    def find_one(self, collection: str, flt: dict | None = None
                 ) -> dict | None:
        rows = self.find(collection, flt, limit=1)
        return rows[0] if rows else None

    def update_many(self, collection: str, flt: dict, update: dict) -> int:
        if not any(k.startswith("$") for k in update):
            update = {"$set": update}
        reply = self.command({
            "update": collection,
            "updates": [{"q": flt, "u": update, "multi": True}]})
        return int(reply.get("nModified", reply.get("n", 0)))

    def delete_many(self, collection: str, flt: dict) -> int:
        reply = self.command({
            "delete": collection,
            "deletes": [{"q": flt, "limit": 0}]})
        return int(reply.get("n", 0))

    def count_documents(self, collection: str,
                        flt: dict | None = None) -> int:
        reply = self.command({"count": collection, "query": flt or {}})
        return int(reply.get("n", 0))

    def drop(self, collection: str) -> None:
        self.command({"drop": collection})

    def health_check(self) -> dict[str, Any]:
        try:
            self.command({"ping": 1})
            return {"status": "UP",
                    "details": {"addr": f"{self.host}:{self.port}",
                                "database": self.database}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------ mini server

class MiniMongoServer:
    """Threaded OP_MSG server over the embedded DocumentEngine."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.engine = DocumentEngine()
        self._server: socket.socket | None = None
        self._conns: list[socket.socket] = []
        self._running = False
        self._lock = threading.Lock()

    def start(self) -> None:
        self._server = socket.create_server((self.host, self.port))
        self.port = self._server.getsockname()[1]
        self._running = True
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="mini-mongo").start()

    def _accept_loop(self) -> None:
        assert self._server is not None
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                break
            self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        buf = bytearray()
        try:
            while True:
                frame = _read_frame(conn, buf)
                if frame is None:
                    break
                request_id, _, body = decode_op_msg(frame)
                try:
                    with self._lock:
                        reply = self._execute(body)
                except Exception as exc:
                    reply = {"ok": 0.0, "errmsg": str(exc)}
                conn.sendall(encode_op_msg(0, reply,
                                           response_to=request_id))
        except (OSError, MongoWireError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _execute(self, body: dict) -> dict:
        e = self.engine
        if "ping" in body:
            return {"ok": 1.0}
        if "insert" in body:
            coll = body["insert"]
            for doc in body.get("documents", []):
                e.insert(coll, doc)       # honors a client-sent _id
            return {"ok": 1.0, "n": len(body.get("documents", []))}
        if "find" in body:
            coll = body["find"]
            rows = e.find(coll, body.get("filter") or None,
                          limit=body.get("limit") or None)
            return {"ok": 1.0, "cursor": {
                "firstBatch": rows, "id": 0,
                "ns": f"db.{coll}"}}
        if "update" in body:
            coll = body["update"]
            n = 0
            for upd in body.get("updates", []):
                changes = upd.get("u", {}).get("$set", {})
                n += e.update(coll, upd.get("q") or {}, changes)
            return {"ok": 1.0, "n": n, "nModified": n}
        if "delete" in body:
            coll = body["delete"]
            n = 0
            for d in body.get("deletes", []):
                n += e.delete(coll, d.get("q") or {})
            return {"ok": 1.0, "n": n}
        if "count" in body:
            coll = body["count"]
            flt = body.get("query") or {}
            if flt:
                return {"ok": 1.0, "n": len(e.find(coll, flt))}
            return {"ok": 1.0, "n": e.count(coll)}
        if "drop" in body:
            e.drop(body["drop"])
            return {"ok": 1.0}
        return {"ok": 0.0, "errmsg": f"unknown command {next(iter(body))}"}

    def close(self) -> None:
        self._running = False
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        self._conns.clear()
