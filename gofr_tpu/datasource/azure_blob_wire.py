"""Azure Blob Storage network client speaking the Blob REST API with
real SharedKey request signing, plus a signature-verifying mini server.

The reference's Azure module is a driver-backed network client
(datasource/file/azure over azure-sdk-for-go). This client speaks the
Blob service REST surface directly — ``PUT`` block blobs, ``GET``/
``DELETE`` blobs, container listing with ``NextMarker`` pagination —
and signs every request with the SharedKey scheme implemented from the
specification (canonicalized x-ms-* headers + canonicalized resource →
HMAC-SHA256 with the base64 account key), behind the same method
surface as the embedded
:class:`~gofr_tpu.datasource.object_store.AzureBlobFileSystem`
adapter, so swapping is a constructor change.

:class:`MiniAzureBlobServer` re-derives and verifies each request's
SharedKey signature against the configured account key — a wrong key
is a 403, exactly like real Azure.
"""

from __future__ import annotations

import base64
import datetime as _dt
import hashlib
import hmac
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Any

from . import Instrumented
from .miniserver import ThreadedHTTPMiniServer
from .object_store import FileError, ObjectNotFound, ObjectStoreEngine

_API_VERSION = "2021-08-06"
# real Azure truncates listings at 5000 blobs per page
_PAGE_SIZE = 5000


class AzureBlobError(FileError):
    pass


def sign_shared_key(method: str, path: str, query: dict[str, str],
                    headers: dict[str, str], *, account: str,
                    key_b64: str) -> str:
    """-> the ``SharedKey account:signature`` Authorization value, per
    the Blob service authorization specification."""
    h = {k.lower(): v.strip() for k, v in headers.items()}
    get = h.get
    canonical_headers = "".join(
        f"{name}:{h[name]}\n"
        for name in sorted(n for n in h if n.startswith("x-ms-")))
    canonical_resource = f"/{account}{path}"
    for name in sorted(query):
        canonical_resource += f"\n{name.lower()}:{query[name]}"
    string_to_sign = "\n".join([
        method.upper(),
        get("content-encoding", ""), get("content-language", ""),
        get("content-length", ""), get("content-md5", ""),
        get("content-type", ""), get("date", ""),
        get("if-modified-since", ""), get("if-match", ""),
        get("if-none-match", ""), get("if-unmodified-since", ""),
        get("range", ""),
    ]) + "\n" + canonical_headers + canonical_resource
    digest = hmac.new(base64.b64decode(key_b64), string_to_sign.encode(),
                      hashlib.sha256).digest()
    return f"SharedKey {account}:{base64.b64encode(digest).decode()}"


class AzureBlobWire(Instrumented):
    """SharedKey-signed REST client with the embedded adapter's verbs
    (upload_blob/download_blob/delete_blob/list_blob_names)."""

    metric = "app_azure_blob_stats"
    log_tag = "AZBLOB"

    def __init__(self, *, endpoint: str = "", account: str = "devaccount",
                 key_b64: str = "", container: str = "gofr",
                 timeout_s: float = 30.0) -> None:
        endpoint = endpoint or f"https://{account}.blob.core.windows.net"
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.account = account
        self.key_b64 = key_b64
        self.container = container
        self.timeout_s = timeout_s

    def connect(self) -> None:
        if self.logger is not None:
            self.logger.info("connected to azure blob",
                             endpoint=self.endpoint,
                             container=self.container)

    def close(self) -> None:
        pass  # per-request connections

    def _call(self, method: str, path: str, query: dict[str, str],
              body: bytes = b"",
              extra_headers: dict[str, str] | None = None
              ) -> tuple[int, bytes]:
        now = _dt.datetime.now(_dt.timezone.utc).strftime(
            "%a, %d %b %Y %H:%M:%S GMT")
        # Content-Type must be set explicitly: urllib would otherwise
        # inject its form-encoded default AFTER signing, and the server
        # (which signs what it received) would compute a different MAC
        headers = {"x-ms-date": now, "x-ms-version": _API_VERSION,
                   "Content-Type": "application/octet-stream"}
        headers.update(extra_headers or {})
        # post-2015 API versions sign an EMPTY Content-Length for 0
        headers["Content-Length"] = str(len(body)) if body else ""
        headers["Authorization"] = sign_shared_key(
            method, path, query, headers,
            account=self.account, key_b64=self.key_b64)
        if not body:
            del headers["Content-Length"]  # urllib sets the real one
        url = self.endpoint + urllib.parse.quote(path)
        if query:
            url += "?" + urllib.parse.urlencode(query)
        req = urllib.request.Request(url, data=body or None, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read()

    def _blob_path(self, name: str) -> str:
        return f"/{self.container}/{name}"

    # ----------------------------------------------------- native verbs
    def upload_blob(self, name: str, data: bytes,
                    overwrite: bool = True) -> None:
        def op():
            extra = {"x-ms-blob-type": "BlockBlob"}
            if not overwrite:
                extra["If-None-Match"] = "*"
            status, payload = self._call("PUT", self._blob_path(name), {},
                                         body=data, extra_headers=extra)
            if status == 409 or (status == 412 and not overwrite):
                raise AzureBlobError(f"blob exists: {name}")
            if status != 201:
                raise AzureBlobError(
                    f"upload {name} -> {status}: {payload[:200]!r}")
        self._observed("UPLOAD", name, op)

    def download_blob(self, name: str) -> bytes:
        def op():
            status, payload = self._call("GET", self._blob_path(name), {})
            if status == 404:
                raise ObjectNotFound(f"{self.container}/{name}")
            if status != 200:
                raise AzureBlobError(
                    f"download {name} -> {status}: {payload[:200]!r}")
            return payload
        return self._observed("DOWNLOAD", name, op)

    def delete_blob(self, name: str) -> None:
        def op():
            status, payload = self._call("DELETE", self._blob_path(name), {})
            if status == 404:
                raise ObjectNotFound(f"{self.container}/{name}")
            if status not in (200, 202):
                raise AzureBlobError(
                    f"delete {name} -> {status}: {payload[:200]!r}")
        self._observed("DELETE", name, op)

    def list_blob_names(self, prefix: str = "") -> list[str]:
        def op():
            names: list[str] = []
            marker = ""
            while True:  # follow NextMarker pagination to the end
                query = {"restype": "container", "comp": "list",
                         "prefix": prefix}
                if marker:
                    query["marker"] = marker
                status, payload = self._call(
                    "GET", f"/{self.container}", query)
                if status != 200:
                    raise AzureBlobError(
                        f"list -> {status}: {payload[:200]!r}")
                root = ET.fromstring(payload)
                for blob in root.iter("Blob"):
                    names.append(blob.findtext("Name", ""))
                marker = root.findtext("NextMarker") or ""
                if not marker:
                    return names
        return self._observed("LIST", prefix or "*", op)

    def health_check(self) -> dict[str, Any]:
        try:
            status, _ = self._call(
                "GET", f"/{self.container}",
                {"restype": "container", "comp": "list", "prefix": ""})
            return {"status": "UP" if status == 200 else "DOWN",
                    "details": {"endpoint": self.endpoint,
                                "container": self.container}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


# ------------------------------------------------------------- mini server

class MiniAzureBlobServer(ThreadedHTTPMiniServer):
    """The Blob REST surface over the embedded engine. Every request's
    SharedKey signature is re-derived and verified against the account
    key — a wrong key is a 403, like real Azure."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 account: str = "devaccount",
                 key_b64: str = "") -> None:
        super().__init__(host, port)
        self.account = account
        self.key_b64 = key_b64 or base64.b64encode(b"mini-key").decode()
        self.engine = ObjectStoreEngine()

    def _verify(self, request) -> bool:
        got = request.headers.get("authorization", "")
        headers = {name: value for name, value in request.headers.items()}
        body = request.body or b""
        headers["content-length"] = str(len(body)) if body else ""
        expect = sign_shared_key(
            request.method, request.path,
            {k: v[0] for k, v in request.query.items()},
            headers, account=self.account, key_b64=self.key_b64)
        return hmac.compare_digest(got, expect)

    def handle(self, request) -> tuple[int, bytes, str]:
        if not self._verify(request):
            return 403, (b"<Error><Code>AuthenticationFailed</Code>"
                         b"</Error>"), "application/xml"
        parts = request.path.lstrip("/").split("/", 1)
        container = parts[0]
        name = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        if not name and request.param("comp") == "list":
            return self._list(container, request)
        if request.method == "PUT":
            if request.headers.get("if-none-match") == "*" \
                    and self.engine.exists(container, name):
                return 412, (b"<Error><Code>BlobAlreadyExists</Code>"
                             b"</Error>"), "application/xml"
            self.engine.put(container, name, request.body)
            return 201, b"", "application/xml"
        if request.method == "GET":
            try:
                data = self.engine.get(container, name)
            except ObjectNotFound:
                return 404, (b"<Error><Code>BlobNotFound</Code></Error>"), \
                    "application/xml"
            return 200, data, "application/octet-stream"
        if request.method == "DELETE":
            if not self.engine.exists(container, name):
                return 404, (b"<Error><Code>BlobNotFound</Code></Error>"), \
                    "application/xml"
            self.engine.delete(container, name)
            return 202, b"", "application/xml"
        return 400, b"<Error><Code>BadRequest</Code></Error>", \
            "application/xml"

    def _list(self, container: str, request) -> tuple[int, bytes, str]:
        prefix = request.param("prefix")
        marker = request.param("marker")
        rows = sorted(self.engine.list(container, prefix))
        if marker:  # opaque marker = last name of the previous page
            rows = [r for r in rows if r[0] > marker]
        page, rest = rows[:_PAGE_SIZE], rows[_PAGE_SIZE:]
        root = ET.Element("EnumerationResults")
        blobs = ET.SubElement(root, "Blobs")
        for key, size, _mtime in page:
            blob = ET.SubElement(blobs, "Blob")
            ET.SubElement(blob, "Name").text = key
            props = ET.SubElement(blob, "Properties")
            ET.SubElement(props, "Content-Length").text = str(size)
        if rest and page:
            ET.SubElement(root, "NextMarker").text = page[-1][0]
        return 200, ET.tostring(root), "application/xml"
