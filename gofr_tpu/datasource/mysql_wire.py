"""MySQL network client speaking the client/server protocol, plus a
mini server.

The reference's SQL datasource dials mysql through database/sql +
go-sql-driver (sql.go:22-35); this client implements the protocol
itself: 3-byte-length + sequence packet framing, the v10 initial
handshake, ``mysql_native_password`` challenge-response auth
(``SHA1(pw) XOR SHA1(scramble + SHA1(SHA1(pw)))``), and the COM_QUERY
text protocol — OK / ERR / result-set packets with length-encoded
columns and NULLs. ``?`` placeholders are expanded to escaped literals
client-side (the text-protocol technique), and the method surface
mirrors :class:`~gofr_tpu.datasource.sql.SQL`
(query/query_row/exec/select/begin/health_check), selected by
``DB_DIALECT=mysql`` + ``DB_HOST``.

:class:`MiniMySQLServer` implements the server half over sqlite —
real handshake bytes, verified auth (wrong password → ERR 1045), the
same result-set encoding mysqld produces.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import socket
import socketserver
import sqlite3
import struct
import threading
import time
from typing import Any, Iterator

from contextlib import contextmanager

from . import ProviderMixin
from .sql import QueryLog, SQLError

CAP_LONG_PASSWORD = 0x0001
CAP_PROTOCOL_41 = 0x0200
CAP_SECURE_CONNECTION = 0x8000
CAP_PLUGIN_AUTH = 0x80000

_CAPS = CAP_LONG_PASSWORD | CAP_PROTOCOL_41 | CAP_SECURE_CONNECTION \
    | CAP_PLUGIN_AUTH

COM_QUIT = 0x01
COM_QUERY = 0x03
COM_PING = 0x0E

TYPE_DOUBLE = 0x05
TYPE_LONGLONG = 0x08
TYPE_BLOB = 0xFC
TYPE_VAR_STRING = 0xFD


class MySQLError(SQLError):
    def __init__(self, message: str, code: int = 0,
                 sqlstate: str = "") -> None:
        super().__init__(message)
        self.code = code
        self.sqlstate = sqlstate


# ------------------------------------------------------------- primitives

def lenenc(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def read_lenenc(data: bytes, off: int) -> tuple[int | None, int]:
    first = data[off]
    off += 1
    if first < 0xFB:
        return first, off
    if first == 0xFB:
        return None, off  # NULL
    if first == 0xFC:
        return struct.unpack_from("<H", data, off)[0], off + 2
    if first == 0xFD:
        return int.from_bytes(data[off:off + 3], "little"), off + 3
    return struct.unpack_from("<Q", data, off)[0], off + 8


def native_password_scramble(password: str, scramble: bytes) -> bytes:
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(scramble + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def escape_literal(value: Any) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, (int, float)):
        return str(value)
    if isinstance(value, bytes):
        return "x'" + value.hex() + "'"
    text = str(value)
    for ch, esc in (("\\", "\\\\"), ("'", "\\'"), ("\0", "\\0"),
                    ("\n", "\\n"), ("\r", "\\r"), ("\x1a", "\\Z")):
        text = text.replace(ch, esc)
    return f"'{text}'"


def expand_qmarks(stmt: str, args: tuple) -> str:
    """``?`` -> escaped literals, skipping string literals, backtick
    identifiers, and ``--``/``#``/``/* */`` comments."""
    out: list[str] = []
    it = iter(args)
    quote: str | None = None  # ' " or ` while inside one
    i = 0
    while i < len(stmt):
        ch = stmt[i]
        if quote is not None:
            out.append(ch)
            if ch == "\\" and quote != "`" and i + 1 < len(stmt):
                out.append(stmt[i + 1])
                i += 1
            elif ch == quote:
                quote = None
        elif ch in ("'", '"', "`"):
            quote = ch
            out.append(ch)
        elif ch == "#" or (stmt[i:i + 2] == "--"
                           and (i + 2 >= len(stmt)
                                or stmt[i + 2] in " \t\n")):
            end = stmt.find("\n", i)
            end = len(stmt) if end == -1 else end
            out.append(stmt[i:end])
            i = end
            continue
        elif stmt[i:i + 2] == "/*":
            end = stmt.find("*/", i + 2)
            end = len(stmt) if end == -1 else end + 2
            out.append(stmt[i:end])
            i = end
            continue
        elif ch == "?":
            try:
                out.append(escape_literal(next(it)))
            except StopIteration:
                raise MySQLError("more ? placeholders than arguments") \
                    from None
        else:
            out.append(ch)
        i += 1
    leftover = sum(1 for _ in it)
    if leftover:
        raise MySQLError(f"{leftover} unused bind arguments")
    return "".join(out)


class _Packets:
    """MySQL packet framing: 3-byte length + sequence id."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._buf = b""
        self.seq = 0

    def _exactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise MySQLError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def recv(self) -> bytes:
        out = b""
        while True:  # 0xFFFFFF-length packets continue in the next one
            header = self._exactly(4)
            length = int.from_bytes(header[:3], "little")
            self.seq = header[3] + 1
            out += self._exactly(length)
            if length < 0xFFFFFF:
                return out

    def send(self, payload: bytes) -> None:
        while True:  # split >=16MB payloads per the protocol
            chunk, payload = payload[:0xFFFFFF], payload[0xFFFFFF:]
            self._sock.sendall(len(chunk).to_bytes(3, "little")
                               + bytes([self.seq & 0xFF]) + chunk)
            self.seq += 1
            if len(chunk) < 0xFFFFFF:
                return

    def reset(self) -> None:
        self.seq = 0


class MySQLRow(dict):
    """Mapping row with ``keys()`` — the sqlite3.Row subset callers use."""

    __slots__ = ()


# ---------------------------------------------------------------- client

class MySQLWire(ProviderMixin):
    """Text-protocol mysql client behind the SQL datasource surface."""

    dialect = "mysql"

    def __init__(self, *, host: str = "localhost", port: int = 3306,
                 user: str = "root", password: str = "",
                 database: str = "", timeout_s: float = 30.0) -> None:
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.database = database
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._packets: _Packets | None = None
        self._lock = threading.RLock()
        self.server_version = ""

    # ------------------------------------------------------------ connect
    def connect(self) -> None:
        if self._sock is not None:
            self.close()
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        packets = _Packets(sock)
        try:
            greeting = packets.recv()
            if greeting[:1] == b"\xff":
                raise self._err(greeting)
            if greeting[0] != 10:
                raise MySQLError(
                    f"unsupported protocol version {greeting[0]}")
            off = 1
            end = greeting.index(b"\0", off)
            self.server_version = greeting[off:end].decode()
            off = end + 1 + 4  # thread id
            scramble = greeting[off:off + 8]
            off += 8 + 1  # filler
            off += 2 + 1 + 2 + 2  # caps low, charset, status, caps high
            auth_len = greeting[off]
            off += 1 + 10  # reserved
            tail = max(13, auth_len - 8) if auth_len else 13
            part2 = greeting[off:off + tail]
            if part2.endswith(b"\0"):  # exactly the one terminator —
                part2 = part2[:-1]      # scramble bytes may BE 0x00
            scramble = (scramble + part2)[:20]

            token = native_password_scramble(self.password, scramble)
            caps = _CAPS | (0x08 if self.database else 0)  # CONNECT_WITH_DB
            response = struct.pack("<IIB23x", caps, 1 << 24, 0x21)
            response += self.user.encode() + b"\0"
            response += bytes([len(token)]) + token
            if self.database:
                response += self.database.encode() + b"\0"
            response += b"mysql_native_password\0"
            packets.send(response)
            reply = packets.recv()
            if reply[:1] == b"\xfe" and len(reply) > 1:
                # AuthSwitchRequest (mysql 8 defaults to
                # caching_sha2_password): switch back to
                # mysql_native_password when offered, else fail clearly
                end = reply.index(b"\0", 1)
                plugin = reply[1:end].decode()
                if plugin != "mysql_native_password":
                    raise MySQLError(
                        f"server requires auth plugin {plugin!r}; only "
                        "mysql_native_password is supported")
                new_scramble = reply[end + 1:]
                if new_scramble.endswith(b"\0"):
                    new_scramble = new_scramble[:-1]
                new_scramble = new_scramble[:20]
                packets.send(native_password_scramble(
                    self.password, new_scramble))
                reply = packets.recv()
            if reply[:1] == b"\xff":
                raise self._err(reply)
            if reply[:1] != b"\x00":
                raise MySQLError(
                    f"unexpected auth reply {reply[:1].hex()}")
            self._packets = packets
        except BaseException:
            sock.close()
            self._sock = None
            self._packets = None
            raise
        if self.logger is not None:
            self.logger.info("connected to mysql", host=self.host,
                             port=self.port, database=self.database)

    @staticmethod
    def _err(payload: bytes) -> MySQLError:
        code = struct.unpack_from("<H", payload, 1)[0]
        off = 3
        sqlstate = ""
        if payload[off:off + 1] == b"#":
            sqlstate = payload[off + 1:off + 6].decode()
            off += 6
        return MySQLError(payload[off:].decode("utf-8", "replace"),
                          code=code, sqlstate=sqlstate)

    def close(self) -> None:
        if self._sock is not None:
            try:
                if self._packets is not None:
                    self._packets.reset()
                    self._packets.send(bytes([COM_QUIT]))
            except OSError:
                pass
            self._sock.close()
            self._sock = None
            self._packets = None

    # ------------------------------------------------------------- query
    def _com_query(self, sql: str) -> tuple[list[MySQLRow], int]:
        """-> (rows, affected)."""
        if self._packets is None:
            raise MySQLError("not connected; call connect() first")
        packets = self._packets
        try:
            packets.reset()
            packets.send(bytes([COM_QUERY]) + sql.encode())
            first = packets.recv()
            if first[:1] == b"\xff":
                raise self._err(first)
            if first[:1] == b"\x00":  # OK packet
                affected, off = read_lenenc(first, 1)
                return [], affected or 0
            ncols, _ = read_lenenc(first, 0)
            columns = []
            for _ in range(ncols or 0):
                columns.append(self._column_def(packets.recv()))
            payload = packets.recv()  # EOF closing the column block
            if not (payload[:1] == b"\xfe" and len(payload) < 9):
                raise MySQLError("expected EOF after column definitions")
            rows: list[MySQLRow] = []
            while True:
                payload = packets.recv()
                if payload[:1] == b"\xfe" and len(payload) < 9:  # EOF
                    return rows, 0
                if payload[:1] == b"\xff":
                    raise self._err(payload)
                row = MySQLRow()
                off = 0
                for name, type_id in columns:
                    value, off = self._read_value(payload, off, type_id)
                    row[name] = value
                rows.append(row)
        except (OSError, TimeoutError) as exc:
            self.close()  # poisoned stream: replies would misalign
            raise MySQLError(
                f"connection lost mid-query ({exc})") from exc
        except MySQLError as exc:
            # server ERR packets (code != 0) leave the stream aligned;
            # structural errors (code 0) mean unread packets remain
            if exc.code == 0:
                self.close()
            raise
        except (struct.error, IndexError) as exc:
            self.close()
            raise MySQLError(f"malformed packet ({exc})") from exc

    @staticmethod
    def _column_def(payload: bytes) -> tuple[str, int]:
        """-> (name, type byte) from a column-definition packet."""
        off = 0
        for _ in range(4):  # catalog, schema, table, org_table
            n, off = read_lenenc(payload, off)
            off += n or 0
        n, off = read_lenenc(payload, off)
        name = payload[off:off + (n or 0)].decode()
        off += n or 0
        n, off = read_lenenc(payload, off)  # org_name
        off += n or 0
        off += 1 + 2 + 4  # fixed-len marker, charset, column length
        type_id = payload[off] if off < len(payload) else TYPE_VAR_STRING
        return name, type_id

    @staticmethod
    def _read_value(payload: bytes, off: int,
                    type_id: int) -> tuple[Any, int]:
        n, off = read_lenenc(payload, off)
        if n is None:
            return None, off
        raw = payload[off:off + n]
        off += n
        try:
            if type_id in (TYPE_LONGLONG, 0x01, 0x02, 0x03, 0x09):
                return int(raw), off
            if type_id in (TYPE_DOUBLE, 0x04, 0x00):  # double/float/dec
                return float(raw), off
            if type_id == TYPE_BLOB:
                return bytes(raw), off
        except ValueError:
            pass  # mixed-type sqlite column behind the mini server
        return raw.decode("utf-8", "surrogateescape"), off

    # --------------------------------------------------- public surface
    def _observe(self, query: str, args: tuple, start: float) -> None:
        duration_us = int((time.perf_counter() - start) * 1e6)
        if self.logger is not None:
            self.logger.debug(
                QueryLog(query, duration_us, args).pretty_print())
        if self.metrics is not None:
            word = query.split(None, 1)[0].lower() if query.split() else "?"
            self.metrics.record_histogram("app_sql_stats",
                                          duration_us / 1e6, type=word)

    def ph(self, n: int) -> str:
        return "?"

    def query(self, query: str, *args: Any) -> list[MySQLRow]:
        start = time.perf_counter()
        span = (self.tracer.start_span(f"sql {query.split(None, 1)[0]}")
                if self.tracer is not None else None)
        try:
            with self._lock:
                rows, _ = self._com_query(expand_qmarks(query, args))
                return rows
        finally:
            if span is not None:
                span.end()
            self._observe(query, args, start)

    def query_row(self, query: str, *args: Any) -> MySQLRow | None:
        rows = self.query(query, *args)
        return rows[0] if rows else None

    def exec(self, query: str, *args: Any) -> "MySQLResult":
        start = time.perf_counter()
        span = (self.tracer.start_span(f"sql {query.split(None, 1)[0]}")
                if self.tracer is not None else None)
        try:
            with self._lock:
                _, affected = self._com_query(expand_qmarks(query, args))
                return MySQLResult(affected)
        finally:
            if span is not None:
                span.end()
            self._observe(query, args, start)

    @contextmanager
    def begin(self) -> Iterator["MySQLWire"]:
        with self._lock:
            self._com_query("BEGIN")
            try:
                yield self
                self._com_query("COMMIT")
            except BaseException:
                if self._sock is not None:
                    self._com_query("ROLLBACK")
                raise

    def select(self, entity_type: type, query: str, *args: Any) -> list[Any]:
        from dataclasses import fields, is_dataclass
        if not is_dataclass(entity_type):
            raise SQLError("select requires a dataclass type")
        out = []
        for row in self.query(query, *args):
            kwargs = {}
            for f in fields(entity_type):
                if f.name in row and row[f.name] is not None:
                    value = row[f.name]
                    if f.type in (int, "int"):
                        value = int(value)
                    elif f.type in (float, "float"):
                        value = float(value)
                    kwargs[f.name] = value
            out.append(entity_type(**kwargs))
        return out

    def health_check(self) -> dict[str, Any]:
        try:
            self.query("SELECT 1")
            return {"status": "UP",
                    "details": {"host": self.host, "port": self.port,
                                "database": self.database,
                                "server": self.server_version}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


class MySQLResult:
    def __init__(self, rowcount: int) -> None:
        self.rowcount = rowcount


# ------------------------------------------------------------ mini server

_BACKSLASH_MAP = {"n": "\n", "r": "\r", "t": "\t", "0": "\0",
                  "Z": "\x1a", "\\": "\\", "'": "'", '"': '"',
                  "%": "\\%", "_": "\\_"}


def _mysql_to_sqlite(sql: str) -> str:
    """Translate MySQL string-literal syntax (backslash escapes,
    double-quoted strings) into sqlite's, the way mysqld's lexer would
    read it."""
    out: list[str] = []
    quote: str | None = None
    i = 0
    while i < len(sql):
        ch = sql[i]
        if quote is None:
            if ch in ("'", '"'):
                quote = ch
                out.append("'")  # double-quoted strings become single
            else:
                out.append(ch)
        elif ch == "\\" and i + 1 < len(sql):
            mapped = _BACKSLASH_MAP.get(sql[i + 1], sql[i + 1])
            out.append("''" if mapped == "'" else mapped)
            i += 1
        elif ch == quote:
            # '' / "" is an escaped delimiter inside the literal
            if i + 1 < len(sql) and sql[i + 1] == quote:
                out.append("''" if quote == "'" else quote)
                i += 1
            else:
                quote = None
                out.append("'")
        elif ch == "'":
            out.append("''")  # ' inside a "..." literal
        else:
            out.append(ch)
        i += 1
    return "".join(out)


class _MySQLHandler(socketserver.BaseRequestHandler):
    @property
    def mini(self) -> "MiniMySQLServer":
        return self.server.mini  # type: ignore[attr-defined]

    def handle(self) -> None:
        import os
        packets = _Packets(self.request)
        try:
            # real mysqld salts avoid NUL (it terminates the field)
            scramble = bytes(b % 255 + 1 for b in os.urandom(20))
            greeting = bytes([10]) + b"8.0-mini\0" \
                + struct.pack("<I", 1) + scramble[:8] + b"\0" \
                + struct.pack("<H", _CAPS & 0xFFFF) + bytes([0x21]) \
                + struct.pack("<H", 2) \
                + struct.pack("<H", (_CAPS >> 16) & 0xFFFF) \
                + bytes([21]) + b"\0" * 10 + scramble[8:] + b"\0" \
                + b"mysql_native_password\0"
            packets.send(greeting)
            response = packets.recv()
            off = 4 + 4 + 1 + 23
            end = response.index(b"\0", off)
            user = response[off:end].decode()
            off = end + 1
            token_len = response[off]
            token = response[off + 1:off + 1 + token_len]
            expect = native_password_scramble(
                self.mini.password, scramble)
            if user != self.mini.user or not hmac_mod.compare_digest(
                    token, expect):
                packets.send(
                    b"\xff" + struct.pack("<H", 1045) + b"#28000"
                    + b"Access denied")
                return
            packets.send(b"\x00\x00\x00" + struct.pack("<HH", 2, 0))

            conn = self.mini.new_conn()
            try:
                while True:
                    packets.reset()
                    command = packets.recv()
                    if not command or command[0] == COM_QUIT:
                        return
                    if command[0] == COM_PING:
                        packets.send(b"\x00\x00\x00"
                                     + struct.pack("<HH", 2, 0))
                        continue
                    if command[0] != COM_QUERY:
                        packets.send(
                            b"\xff" + struct.pack("<H", 1047) + b"#08S01"
                            + b"unsupported command")
                        continue
                    self._query(packets, conn, command[1:].decode())
            finally:
                conn.close()
        except (MySQLError, ConnectionError, OSError):
            return

    def _query(self, packets: _Packets, conn: sqlite3.Connection,
               sql: str) -> None:
        try:
            with self.mini.lock:
                cur = conn.execute(_mysql_to_sqlite(sql))
                rows = cur.fetchall()
        except sqlite3.Error as exc:
            packets.send(b"\xff" + struct.pack("<H", 1064) + b"#42000"
                         + str(exc).encode())
            return
        if cur.description is None:
            affected = cur.rowcount if cur.rowcount > 0 else 0
            packets.send(b"\x00" + lenenc(affected) + lenenc(0)
                         + struct.pack("<HH", 2, 0))
            return
        names = [d[0] for d in cur.description]
        packets.send(lenenc(len(names)))
        for idx, name in enumerate(names):
            sample = next((row[idx] for row in rows
                           if row[idx] is not None), None)
            if isinstance(sample, int) and not isinstance(sample, bool):
                type_id = TYPE_LONGLONG
            elif isinstance(sample, float):
                type_id = TYPE_DOUBLE
            elif isinstance(sample, bytes):
                type_id = TYPE_BLOB
            else:
                type_id = TYPE_VAR_STRING
            payload = b""
            for field in ("def", "", "t", "t"):
                payload += lenenc(len(field)) + field.encode()
            payload += lenenc(len(name)) + name.encode()
            payload += lenenc(len(name)) + name.encode()
            payload += bytes([0x0C]) + struct.pack("<H", 0x21) \
                + struct.pack("<I", 1024) + bytes([type_id]) \
                + struct.pack("<H", 0) + bytes([0, 0, 0])
            packets.send(payload)
        packets.send(b"\xfe" + struct.pack("<HH", 0, 2))  # EOF
        for row in rows:
            payload = b""
            for value in row:
                if value is None:
                    payload += b"\xfb"
                else:
                    if isinstance(value, bytes):
                        data = value
                    else:
                        data = str(value).encode()
                    payload += lenenc(len(data)) + data
            packets.send(payload)
        packets.send(b"\xfe" + struct.pack("<HH", 0, 2))  # EOF


class MiniMySQLServer:
    """Server half of the mysql protocol over a shared-cache sqlite
    database (one connection per client, like
    :class:`~gofr_tpu.datasource.postgres_wire.MiniPostgresServer`)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 user: str = "root", password: str = "secret") -> None:
        import os
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self._db_uri = (f"file:minimysql_{os.getpid()}_{id(self):x}"
                        "?mode=memory&cache=shared")
        self._anchor = self.new_conn()
        self.lock = threading.RLock()
        self._server: socketserver.ThreadingTCPServer | None = None
        self._thread: threading.Thread | None = None

    def new_conn(self) -> sqlite3.Connection:
        return sqlite3.connect(self._db_uri, uri=True,
                               check_same_thread=False,
                               isolation_level=None)

    def start(self) -> None:
        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = TCP((self.host, self.port), _MySQLHandler)
        self._server.mini = self  # the handler reads this back
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="mini-mysql")
        self._thread.start()

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self._anchor.close()
