"""Oracle wire client — TNS transport + O5LOGON-style auth.

The reference's oracle module wraps ``database/sql`` + the godror
driver (/root/reference/pkg/gofr/datasource/oracle/oracle.go:74-145,
interface.go:5-16); the driver speaks Oracle's TNS/TTC protocol. This
module implements the wire layers whose formats are publicly
documented, to the same bar as the repo's other wire clients:

- **TNS packet layer** (the Transparent Network Substrate framing
  every Oracle connection uses): 8-byte header ``!HHBBH`` =
  packet length, checksum, packet type, flags, header checksum;
  CONNECT (0x01, carrying the ``(DESCRIPTION=...)`` connect
  descriptor), ACCEPT (0x02), REFUSE (0x04, ORA- error payload),
  DATA (0x06, 2-byte data flags), MARKER (0x0C, break/reset pairs),
  RESEND (0x0B).
- **O5LOGON-shaped auth** (the 11g+ challenge-response): the server
  sends ``AUTH_VFR_DATA`` (password salt) and ``AUTH_SESSKEY`` — a
  random session half AES-192-CBC-encrypted under a key derived from
  the password verifier ``SHA1(password || salt)``; the client
  decrypts it, generates its own half, returns it encrypted the same
  way, and both sides derive the combined key that encrypts
  ``AUTH_PASSWORD``. A wrong password fails to decrypt and the server
  refuses with ORA-01017.
- **Statement layer**: Oracle's inner TTC RPC encoding is proprietary
  and undocumented; statements + ``:1``-style binds ride DATA packets
  in a compact length-prefixed form documented here (`_wire_fields`),
  with ORA-coded errors and DUAL supported by the mini server's
  engine. The framing above it is byte-faithful TNS.

Interface parity with the reference Connection/Txn (interface.go):
``select``/``exec``/``ping``/``begin``/``commit``/``rollback``, plus
the provider pattern and per-op stats every repo datasource records.
"""

from __future__ import annotations

import hashlib
import os
import socket
import struct
import threading
import time
from contextlib import contextmanager
from dataclasses import fields as dc_fields, is_dataclass
from typing import Any, Iterator

from . import Instrumented

# ------------------------------------------------------------- TNS layer

TNS_CONNECT = 1
TNS_ACCEPT = 2
TNS_REFUSE = 4
TNS_DATA = 6
TNS_RESEND = 11
TNS_MARKER = 12

TNS_VERSION = 314          # 0x013A, the 11g+ wire version
DATA_FLAG_EOF = 0x0040

MARKER_BREAK = 1
MARKER_RESET = 2


class OracleError(Exception):
    def __init__(self, message: str, code: int = 0) -> None:
        super().__init__(message)
        self.code = code                      # ORA-xxxxx number


class _Stream:
    """Fragmentation-safe reader (the byte-dribble torture contract)."""

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock

    def exactly(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise OracleError("connection closed mid-packet", 3113)
            buf += chunk
        return buf


def send_packet(sock: socket.socket, ptype: int, payload: bytes) -> None:
    header = struct.pack("!HHBBH", 8 + len(payload), 0, ptype, 0, 0)
    sock.sendall(header + payload)


def recv_packet(stream: _Stream) -> tuple[int, bytes]:
    header = stream.exactly(8)
    length, _csum, ptype, _flags, _hcsum = struct.unpack("!HHBBH", header)
    if not 8 <= length <= 0xFFFF:
        raise OracleError(f"TNS packet length {length} out of bounds", 12592)
    return ptype, stream.exactly(length - 8)


def send_data(sock: socket.socket, payload: bytes, flags: int = 0) -> None:
    send_packet(sock, TNS_DATA, struct.pack("!H", flags) + payload)


def send_marker(sock: socket.socket, kind: int) -> None:
    # marker packets are 3 data bytes: type 1, zero, marker kind
    send_packet(sock, TNS_MARKER, bytes([1, 0, kind]))


# --------------------------------------------------- statement wire form

def _wire_fields(pairs: list[tuple[str, bytes]]) -> bytes:
    """Length-prefixed key/value fields riding a DATA packet."""
    out = b""
    for key, value in pairs:
        kb = key.encode()
        out += struct.pack("!HI", len(kb), len(value)) + kb + value
    return out


def _parse_fields(payload: bytes) -> list[tuple[str, bytes]]:
    out = []
    off = 0
    while off < len(payload):
        if off + 6 > len(payload):
            raise OracleError("truncated field header", 3137)
        klen, vlen = struct.unpack_from("!HI", payload, off)
        off += 6
        if off + klen + vlen > len(payload):
            raise OracleError("truncated field payload", 3137)
        key = payload[off:off + klen].decode()
        off += klen
        out.append((key, payload[off:off + vlen]))
        off += vlen
    return out


# ------------------------------------------------------------ auth crypto

def _pad16(b: bytes) -> bytes:
    pad = 16 - len(b) % 16
    return b + bytes([pad]) * pad


def _unpad16(b: bytes) -> bytes:
    if not b or b[-1] > 16:
        raise OracleError("bad padding", 1017)
    return b[:-b[-1]]


def _aes_cbc(key24: bytes, data: bytes, *, encrypt: bool) -> bytes:
    from cryptography.hazmat.primitives.ciphers import (Cipher, algorithms,
                                                        modes)
    c = Cipher(algorithms.AES(key24), modes.CBC(b"\x00" * 16))
    op = c.encryptor() if encrypt else c.decryptor()
    return op.update(data) + op.finalize()


def _verifier(password: str, salt: bytes) -> bytes:
    """11g-style password verifier: SHA1(password || salt), zero-padded
    to the AES-192 key width."""
    return (hashlib.sha1(password.encode() + salt).digest()
            + b"\x00" * 4)[:24]


def _combined_key(server_half: bytes, client_half: bytes) -> bytes:
    mixed = hashlib.sha1(server_half[:16] + client_half[:16]).digest()
    return (mixed + b"\x00" * 8)[:24]


# ---------------------------------------------------------------- client

class OracleRow(dict):
    __getattr__ = dict.get


class OracleWire(Instrumented):
    """Reference Connection/Txn surface over the TNS transport."""

    metric = "app_oracle_stats"
    log_tag = "ORACLE"
    dialect = "oracle"  # query-builder/auto-CRUD placeholder selection

    def __init__(self, *, host: str = "127.0.0.1", port: int = 1521,
                 service_name: str = "FREEPDB1", username: str = "",
                 password: str = "", timeout_s: float = 10.0) -> None:
        self.host = host
        self.port = port
        self.service_name = service_name
        self.username = username
        self.password = password
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._stream: _Stream | None = None
        self._lock = threading.RLock()

    # ------------------------------------------------------------ session
    def connect(self) -> None:
        with self._lock:
            if self._sock is not None:
                self.close()
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.timeout_s)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            stream = _Stream(sock)
            try:
                self._handshake(sock, stream)
                self._authenticate(sock, stream)
            except BaseException:
                sock.close()
                raise
            self._sock, self._stream = sock, stream
            if self.logger is not None:
                self.logger.info(
                    f"oracle connected {self.host}:{self.port}"
                    f"/{self.service_name}")

    def _handshake(self, sock: socket.socket, stream: _Stream) -> None:
        descriptor = (
            f"(DESCRIPTION=(ADDRESS=(PROTOCOL=TCP)(HOST={self.host})"
            f"(PORT={self.port}))(CONNECT_DATA="
            f"(SERVICE_NAME={self.service_name})(CID=(PROGRAM=gofr_tpu)"
            f"(USER={self.username}))))").encode()
        # CONNECT body: version, lowest compatible version, service
        # options, SDU, TDU, then the descriptor's length + offset
        # (relative to packet start, header included: 8 + 24)
        body = struct.pack("!HHHHHHHH", TNS_VERSION, 300, 0, 8192, 32767,
                           len(descriptor), 32, 0) + b"\x00" * 8 \
            + descriptor
        send_packet(sock, TNS_CONNECT, body)
        ptype, payload = recv_packet(stream)
        if ptype == TNS_RESEND:               # protocol-legal: try again
            send_packet(sock, TNS_CONNECT, body)
            ptype, payload = recv_packet(stream)
        if ptype == TNS_REFUSE:
            raise OracleError(self._refusal(payload), 12514)
        if ptype != TNS_ACCEPT:
            raise OracleError(f"expected ACCEPT, got type {ptype}", 12537)
        (version,) = struct.unpack_from("!H", payload, 0)
        if version > TNS_VERSION:
            raise OracleError(f"server TNS version {version} too new",
                              12516)

    @staticmethod
    def _refusal(payload: bytes) -> str:
        # REFUSE: user reason, system reason, data length, data
        if len(payload) >= 4:
            (dlen,) = struct.unpack_from("!H", payload, 2)
            return payload[4:4 + dlen].decode("latin-1") or "refused"
        return "connection refused"

    def _authenticate(self, sock: socket.socket, stream: _Stream) -> None:
        send_data(sock, _wire_fields([
            ("FUNCTION", b"AUTH_PHASE1"),
            ("AUTH_TERMINAL", b"gofr"),
            ("AUTH_USER", self.username.encode())]))
        reply = dict(self._read_reply(stream, sock))
        salt = bytes.fromhex(reply["AUTH_VFR_DATA"].decode())
        enc_server_key = bytes.fromhex(reply["AUTH_SESSKEY"].decode())

        verifier = _verifier(self.password, salt)
        server_half = _aes_cbc(verifier, enc_server_key, encrypt=False)
        client_half = os.urandom(32)
        combo = _combined_key(server_half, client_half)
        send_data(sock, _wire_fields([
            ("FUNCTION", b"AUTH_PHASE2"),
            ("AUTH_USER", self.username.encode()),
            ("AUTH_SESSKEY", _aes_cbc(verifier, client_half,
                                      encrypt=True).hex().encode()),
            ("AUTH_PASSWORD", _aes_cbc(
                combo, _pad16(self.password.encode()),
                encrypt=True).hex().encode())]))
        reply = dict(self._read_reply(stream, sock))
        if reply.get("STATUS") != b"AUTH_SUCCESS":
            raise OracleError("ORA-01017: invalid username/password; "
                              "logon denied", 1017)

    def _read_reply(self, stream: _Stream,
                    sock: socket.socket) -> list[tuple[str, bytes]]:
        while True:
            ptype, payload = recv_packet(stream)
            if ptype == TNS_MARKER:
                # server break: acknowledge with a reset marker and
                # read on — the error arrives as a DATA reply
                send_marker(sock, MARKER_RESET)
                continue
            if ptype == TNS_REFUSE:
                raise OracleError(self._refusal(payload), 3113)
            if ptype != TNS_DATA:
                raise OracleError(f"unexpected TNS type {ptype}", 3137)
            fields = _parse_fields(payload[2:])
            named = dict(fields)
            if "ORA_ERROR" in named:
                code_s, _, msg = named["ORA_ERROR"].decode().partition(":")
                raise OracleError(msg.strip() or f"ORA-{code_s}",
                                  int(code_s or 0))
            return fields

    # ---------------------------------------------------------- execution
    def _require(self) -> tuple[socket.socket, _Stream]:
        if self._sock is None or self._stream is None:
            raise OracleError("not connected", 3114)
        return self._sock, self._stream

    def _roundtrip(self, op: str, query: str,
                   args: tuple) -> list[tuple[str, bytes]]:
        def go():
            with self._lock:
                sock, stream = self._require()
                pairs = [("FUNCTION", b"EXEC"), ("SQL", query.encode())]
                for arg in args:
                    if arg is None:
                        pairs.append(("BIND_NULL", b""))
                    else:
                        pairs.append(("BIND", str(arg).encode()))
                send_data(sock, _wire_fields(pairs))
                return self._read_reply(stream, sock)
        # Instrumented._observed: QueryLog line + lazily-registered
        # app_oracle_stats histogram, same as every other store
        return self._observed(op.upper(), query, go)

    def ph(self, n: int) -> str:
        return f":{n}"                        # Oracle bind placeholder

    def query(self, query: str, *args: Any) -> list[OracleRow]:
        fields = self._roundtrip("select", query, args)
        cols: list[str] = []
        rows: list[OracleRow] = []
        for key, value in fields:
            if key == "COL":
                cols.append(value.decode())
            elif key == "ROW":
                cells = _parse_fields(value)
                rows.append(OracleRow(
                    {c: (None if k == "NULL" else v.decode())
                     for c, (k, v) in zip(cols, cells)}))
        return rows

    def query_row(self, query: str, *args: Any) -> OracleRow | None:
        rows = self.query(query, *args)
        return rows[0] if rows else None

    def exec(self, query: str, *args: Any) -> int:
        fields = dict(self._roundtrip("exec", query, args))
        return int(fields.get("AFFECTED", b"0") or 0)

    def select(self, entity_type: type, query: str, *args: Any) -> list[Any]:
        """reference interface.go Select: rows into typed entities."""
        if not is_dataclass(entity_type):
            raise OracleError("select requires a dataclass type")
        names = [f.name for f in dc_fields(entity_type)]
        out = []
        for row in self.query(query, *args):
            kw = {}
            for name in names:
                v = row.get(name, row.get(name.upper()))
                kw[name] = v
            out.append(entity_type(**kw))
        return out

    def ping(self) -> None:
        self.query("SELECT 1 FROM DUAL")

    # ------------------------------------------------------- transactions
    @contextmanager
    def begin(self) -> Iterator["OracleWire"]:
        """reference Txn: commit on clean exit, rollback on error."""
        self.exec("BEGIN")
        try:
            yield self
        except BaseException:
            self.exec("ROLLBACK")
            raise
        else:
            self.exec("COMMIT")

    def commit(self) -> None:
        self.exec("COMMIT")

    def rollback(self) -> None:
        self.exec("ROLLBACK")

    # -------------------------------------------------------------- admin
    def health_check(self) -> dict[str, Any]:
        try:
            if self._sock is None:
                self.connect()
            self.ping()
            return {"status": "UP",
                    "details": {"host": f"{self.host}:{self.port}",
                                "service": self.service_name}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        with self._lock:
            sock, self._sock, self._stream = self._sock, None, None
            if sock is not None:
                try:
                    send_data(sock, b"", flags=DATA_FLAG_EOF)
                except OSError:
                    pass
                sock.close()


# ------------------------------------------------------------ mini server

class MiniOracleServer:
    """Protocol-faithful hermetic server: TNS framing, RESEND on first
    connect (the classic Oracle listener behaviour), O5LOGON-style
    challenge-response, markers, ORA-coded errors; statements execute
    on an embedded engine with Oracle affordances (DUAL, :n binds)."""

    def __init__(self, *, service_name: str = "FREEPDB1",
                 users: dict[str, str] | None = None,
                 resend_first: bool = True) -> None:
        import sqlite3
        self.service_name = service_name
        self.users = users or {}
        self.resend_first = resend_first
        self.port = 0
        self._db = sqlite3.connect(":memory:", check_same_thread=False)
        self._db_lock = threading.Lock()
        self._server_sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._closing = False

    def start(self) -> None:
        self._server_sock = socket.socket()
        self._server_sock.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
        self._server_sock.bind(("127.0.0.1", 0))
        self._server_sock.listen(16)
        self.port = self._server_sock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._server_sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    # ------------------------------------------------------- per-session
    def _serve(self, sock: socket.socket) -> None:
        stream = _Stream(sock)
        try:
            if not self._tns_accept(sock, stream):
                return
            user = self._auth(sock, stream)
            if user is None:
                return
            self._statement_loop(sock, stream)
        except (OracleError, OSError, struct.error):
            pass
        finally:
            sock.close()

    def _tns_accept(self, sock: socket.socket, stream: _Stream) -> bool:
        ptype, payload = recv_packet(stream)
        if ptype != TNS_CONNECT:
            return False
        if self.resend_first:
            # real listeners answer a large CONNECT with RESEND once
            send_packet(sock, TNS_RESEND, b"")
            ptype, payload = recv_packet(stream)
            if ptype != TNS_CONNECT:
                return False
        (version,) = struct.unpack_from("!H", payload, 0)
        descriptor = payload[24:].decode("latin-1")
        if f"(SERVICE_NAME={self.service_name})" not in descriptor:
            msg = (f"ORA-12514: listener does not currently know of "
                   f"service requested")
            send_packet(sock, TNS_REFUSE,
                        struct.pack("!BBH", 34, 0, len(msg))
                        + msg.encode())
            return False
        send_packet(sock, TNS_ACCEPT,
                    struct.pack("!HHHH", min(version, TNS_VERSION), 0,
                                8192, 32767))
        return True

    def _auth(self, sock: socket.socket, stream: _Stream) -> str | None:
        fields = dict(self._read_data(stream))
        user = fields.get("AUTH_USER", b"").decode()
        salt = os.urandom(10)
        server_half = os.urandom(32)
        password = self.users.get(user)
        # unknown user: hand out a throwaway verifier anyway — the
        # failure surfaces after phase 2, not as a user oracle
        verifier = _verifier(password if password is not None
                             else os.urandom(8).hex(), salt)
        send_data(sock, _wire_fields([
            ("AUTH_VFR_DATA", salt.hex().encode()),
            ("AUTH_SESSKEY", _aes_cbc(verifier, server_half,
                                      encrypt=True).hex().encode())]))

        fields = dict(self._read_data(stream))
        try:
            client_half = _aes_cbc(
                verifier, bytes.fromhex(fields["AUTH_SESSKEY"].decode()),
                encrypt=False)
            combo = _combined_key(server_half, client_half)
            got = _unpad16(_aes_cbc(
                combo, bytes.fromhex(fields["AUTH_PASSWORD"].decode()),
                encrypt=False)).decode()
        except (KeyError, ValueError, OracleError):
            got = None
        if password is None or got != password:
            send_data(sock, _wire_fields([
                ("ORA_ERROR", b"1017: ORA-01017: invalid username/"
                              b"password; logon denied")]))
            return None
        send_data(sock, _wire_fields([("STATUS", b"AUTH_SUCCESS")]))
        return user

    def _read_data(self, stream: _Stream) -> list[tuple[str, bytes]]:
        while True:
            ptype, payload = recv_packet(stream)
            if ptype == TNS_MARKER:
                continue
            if ptype != TNS_DATA:
                raise OracleError("expected DATA", 3137)
            (flags,) = struct.unpack_from("!H", payload, 0)
            if flags & DATA_FLAG_EOF:
                raise OracleError("client disconnected", 3113)
            return _parse_fields(payload[2:])

    # -------------------------------------------------------- statements
    def _statement_loop(self, sock: socket.socket,
                        stream: _Stream) -> None:
        in_txn = False
        while True:
            fields = self._read_data(stream)
            named = dict(fields)
            sql = named.get("SQL", b"").decode()
            binds = [None if k == "BIND_NULL" else v.decode()
                     for k, v in fields if k in ("BIND", "BIND_NULL")]
            try:
                reply, in_txn = self._execute(sql, binds, in_txn)
            except OracleError as exc:
                # real servers send a break marker, then the error
                send_marker(sock, MARKER_BREAK)
                reply = [("ORA_ERROR",
                          f"{exc.code}: {exc}".encode())]
            send_data(sock, _wire_fields(reply))

    def _execute(self, sql: str, binds: list[str],
                 in_txn: bool) -> tuple[list[tuple[str, bytes]], bool]:
        import sqlite3
        bare = sql.strip().rstrip(";")
        upper = bare.upper()
        with self._db_lock:
            if upper == "BEGIN":
                return [("AFFECTED", b"0")], True
            if upper in ("COMMIT", "ROLLBACK"):
                if in_txn or True:
                    (self._db.commit if upper == "COMMIT"
                     else self._db.rollback)()
                return [("AFFECTED", b"0")], False
            # Oracle affordances over the embedded engine
            stmt = bare
            if upper.endswith("FROM DUAL"):
                stmt = bare[:-len("FROM DUAL") - 1].rstrip()
            for i in range(len(binds), 0, -1):
                stmt = stmt.replace(f":{i}", "?")
            try:
                cur = self._db.execute(stmt, binds)
            except sqlite3.Error as exc:
                raise OracleError(f"ORA-00900: {exc}", 900) from exc
            if cur.description is not None:
                out: list[tuple[str, bytes]] = [
                    ("COL", d[0].upper().encode())
                    for d in cur.description]
                for row in cur.fetchall():
                    cells = [("NULL", b"") if v is None
                             else ("VAL", str(v).encode()) for v in row]
                    out.append(("ROW", _wire_fields(cells)))
                return out, in_txn
            if not in_txn:
                self._db.commit()
            return [("AFFECTED", str(cur.rowcount).encode())], in_txn

    def close(self) -> None:
        self._closing = True
        if self._server_sock is not None:
            try:
                self._server_sock.close()
            except OSError:
                pass
