"""FTP-backed FileSystem (reference datasource/file/ftp) over the
stdlib ``ftplib`` wire client, plus an in-process mini FTP server so
tests drive real protocol bytes (the broker-test philosophy of
pubsub/nats.py applied to file transfer).

SFTP (reference datasource/file/sftp) needs an SSH stack that is not
in this image; :class:`SFTPFileSystem` ships the same surface and
raises a clear error at connect unless given a ready client object
(dependency-injected, mockable — the reference test strategy)."""

from __future__ import annotations

import ftplib
import io
import threading
import time
from typing import Any

from . import Instrumented
from .file_store import FileError, FileInfo, RowReader


class FTPFileSystem(Instrumented):
    metric = "app_file_stats"
    log_tag = "FTP"

    def __init__(self, host: str = "127.0.0.1", port: int = 21,
                 user: str = "anonymous", password: str = "",
                 timeout: float = 10.0) -> None:
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.timeout = timeout
        self._ftp: ftplib.FTP | None = None
        self._lock = threading.RLock()

    def connect(self) -> None:
        ftp = ftplib.FTP()
        ftp.connect(self.host, self.port, timeout=self.timeout)
        ftp.login(self.user, self.password)
        self._ftp = ftp
        if self.logger is not None:
            self.logger.info(f"FTP connected {self.host}:{self.port}")

    def _require(self) -> ftplib.FTP:
        if self._ftp is None:
            raise FileError("FTP not connected")
        return self._ftp

    # ------------------------------------------------ FileSystem surface
    def create(self, path: str, data: bytes | str = b"") -> None:
        payload = data.encode() if isinstance(data, str) else bytes(data)
        def op():
            with self._lock:
                self._require().storbinary(f"STOR {path}",
                                           io.BytesIO(payload))
        self._observed("CREATE", path, op)

    def read(self, path: str) -> bytes:
        def op():
            buf = io.BytesIO()
            with self._lock:
                self._require().retrbinary(f"RETR {path}", buf.write)
            return buf.getvalue()
        return self._observed("READ", path, op)

    def read_text(self, path: str) -> str:
        return self.read(path).decode()

    def append(self, path: str, data: bytes | str) -> None:
        payload = data.encode() if isinstance(data, str) else bytes(data)
        def op():
            with self._lock:
                self._require().storbinary(f"APPE {path}",
                                           io.BytesIO(payload))
        self._observed("APPEND", path, op)

    def remove(self, path: str) -> None:
        def op():
            with self._lock:
                self._require().delete(path)
        self._observed("REMOVE", path, op)

    def rename(self, old: str, new: str) -> None:
        def op():
            with self._lock:
                self._require().rename(old, new)
        self._observed("RENAME", f"{old}->{new}", op)

    def exists(self, path: str) -> bool:
        try:
            self.stat(path)
            return True
        except Exception:
            return False

    def stat(self, path: str) -> FileInfo:
        def op():
            with self._lock:
                size = self._require().size(path)
            if size is None:
                raise FileError(f"no such file: {path}")
            return FileInfo(name=path.rsplit("/", 1)[-1], size=size,
                            is_dir=False, mod_time=time.time())
        return self._observed("STAT", path, op)

    def mkdir(self, path: str) -> None:
        def op():
            with self._lock:
                self._require().mkd(path)
        self._observed("MKDIR", path, op)

    def read_dir(self, path: str = ".") -> list[FileInfo]:
        def op():
            with self._lock:
                names = self._require().nlst(path)
            return [FileInfo(name=n.rsplit("/", 1)[-1], size=0,
                             is_dir=n.endswith("/"), mod_time=0.0)
                    for n in names]
        return self._observed("READ_DIR", path, op)

    def read_rows(self, path: str, kind: str | None = None) -> RowReader:
        text = self.read_text(path)
        if kind is None:
            kind = "csv" if path.endswith(".csv") else "json"
        return RowReader(text, kind)

    def health_check(self) -> dict[str, Any]:
        try:
            with self._lock:
                self._require().voidcmd("NOOP")
            return {"status": "UP",
                    "details": {"backend": "ftp",
                                "addr": f"{self.host}:{self.port}"}}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        if self._ftp is not None:
            try:
                self._ftp.quit()
            except Exception:
                pass
            self._ftp = None


class SFTPFileSystem(FTPFileSystem):
    """Same surface over an injected SFTP client (paramiko-style:
    open/put/get/listdir/remove/rename/mkdir/stat). The SSH stack is
    not baked into this image, so the client arrives from outside —
    production injects paramiko, tests inject a fake."""

    log_tag = "SFTP"

    def __init__(self, client: Any = None, **kw: Any) -> None:
        super().__init__(**kw)
        self._client = client

    def connect(self) -> None:
        if self._client is None:
            raise FileError(
                "SFTP needs an injected client (paramiko SFTPClient-like); "
                "none provided and no SSH stack is bundled")

    def create(self, path: str, data: bytes | str = b"") -> None:
        payload = data.encode() if isinstance(data, str) else bytes(data)
        self._observed("CREATE", path,
                       lambda: self._client.putfo(io.BytesIO(payload), path))

    def read(self, path: str) -> bytes:
        def op():
            buf = io.BytesIO()
            self._client.getfo(path, buf)
            return buf.getvalue()
        return self._observed("READ", path, op)

    def remove(self, path: str) -> None:
        self._observed("REMOVE", path, lambda: self._client.remove(path))

    def rename(self, old: str, new: str) -> None:
        self._observed("RENAME", f"{old}->{new}",
                       lambda: self._client.rename(old, new))

    def read_dir(self, path: str = ".") -> list[FileInfo]:
        def op():
            return [FileInfo(name=n, size=0, is_dir=False, mod_time=0.0)
                    for n in self._client.listdir(path)]
        return self._observed("READ_DIR", path, op)

    def health_check(self) -> dict[str, Any]:
        status = "UP" if self._client is not None else "DOWN"
        return {"status": status, "details": {"backend": "sftp"}}


# ---------------------------------------------------------------- server
class MiniFTPServer:
    """Minimal threaded FTP server for tests: USER/PASS, TYPE, PASV,
    STOR/APPE/RETR/DELE/RNFR+RNTO, SIZE, NLST, MKD, NOOP, QUIT over an
    in-memory tree."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        import socket
        self.host = host
        self._files: dict[str, bytes] = {}
        self._dirs: set[str] = set()
        self._lock = threading.Lock()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._running = True
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn) -> None:
        import socket
        def send(line: str) -> None:
            conn.sendall((line + "\r\n").encode())

        data_listener: socket.socket | None = None
        rename_from: str | None = None
        send("220 mini-ftp ready")
        reader = conn.makefile("rb")
        try:
            while True:
                raw = reader.readline()
                if not raw:
                    break
                parts = raw.decode().strip().split(" ", 1)
                cmd = parts[0].upper()
                arg = parts[1] if len(parts) > 1 else ""
                if cmd == "USER":
                    send("331 password please")
                elif cmd == "PASS":
                    send("230 logged in")
                elif cmd == "TYPE":
                    send("200 type set")
                elif cmd == "NOOP":
                    send("200 ok")
                elif cmd == "PASV":
                    data_listener = socket.socket()
                    data_listener.bind((self.host, 0))
                    data_listener.listen(1)
                    p = data_listener.getsockname()[1]
                    h = self.host.replace(".", ",")
                    send(f"227 entering passive ({h},{p >> 8},{p & 255})")
                elif cmd in ("STOR", "APPE"):
                    if data_listener is None:
                        send("425 use PASV first")
                        continue
                    send("150 ok to send")
                    dconn, _ = data_listener.accept()
                    chunks = []
                    while True:
                        chunk = dconn.recv(65536)
                        if not chunk:
                            break
                        chunks.append(chunk)
                    dconn.close()
                    data_listener.close()
                    data_listener = None
                    with self._lock:
                        if cmd == "APPE":
                            prev = self._files.get(arg, b"")
                            self._files[arg] = prev + b"".join(chunks)
                        else:
                            self._files[arg] = b"".join(chunks)
                    send("226 stored")
                elif cmd == "RETR":
                    with self._lock:
                        data = self._files.get(arg)
                    if data is None:
                        send("550 no such file")
                        continue
                    if data_listener is None:
                        send("425 use PASV first")
                        continue
                    send("150 opening data connection")
                    dconn, _ = data_listener.accept()
                    dconn.sendall(data)
                    dconn.close()
                    data_listener.close()
                    data_listener = None
                    send("226 transfer complete")
                elif cmd == "SIZE":
                    with self._lock:
                        data = self._files.get(arg)
                    if data is None:
                        send("550 no such file")
                    else:
                        send(f"213 {len(data)}")
                elif cmd == "DELE":
                    with self._lock:
                        existed = self._files.pop(arg, None) is not None
                    send("250 deleted" if existed else "550 no such file")
                elif cmd == "RNFR":
                    rename_from = arg
                    send("350 ready for RNTO")
                elif cmd == "RNTO":
                    with self._lock:
                        if rename_from in self._files:
                            self._files[arg] = self._files.pop(rename_from)
                            send("250 renamed")
                        else:
                            send("550 no such file")
                    rename_from = None
                elif cmd == "MKD":
                    with self._lock:
                        self._dirs.add(arg)
                    send(f'257 "{arg}" created')
                elif cmd == "NLST":
                    if data_listener is None:
                        send("425 use PASV first")
                        continue
                    prefix = "" if arg in ("", ".") else arg.rstrip("/") + "/"
                    with self._lock:
                        names = [k for k in sorted(self._files)
                                 if k.startswith(prefix)]
                    send("150 here comes the listing")
                    dconn, _ = data_listener.accept()
                    dconn.sendall("".join(f"{n}\r\n" for n in names).encode())
                    dconn.close()
                    data_listener.close()
                    data_listener = None
                    send("226 done")
                elif cmd == "QUIT":
                    send("221 bye")
                    break
                else:
                    send(f"502 {cmd} not implemented")
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass
