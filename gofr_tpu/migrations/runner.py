"""Versioned migrations over every initialized datasource.

Mirrors reference pkg/gofr/migration/: user supplies
``{version: Migrate(up=fn)}`` (migration.go:14-18); ``run`` sorts
versions, builds a migrator chain over whichever datasources are
initialized (migration.go:118-235), ensures the ``gofr_migrations``
ledger in each store, and applies every version newer than the last
recorded one — SQL transactionally with rollback on failure
(migration.go:59-98). Each migration's ``up`` receives a ``Datasource``
facade so one migration can touch SQL, Redis, KV, and pub/sub topics.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class Migrate:
    up: Callable[["Datasource"], None]


class Datasource:
    """What a migration's ``up`` sees (reference migration/datasource.go):
    the initialized stores plus the logger. Inside ``run`` the SQL
    handle is the open transaction."""

    def __init__(self, *, sql: Any = None, redis: Any = None, kv: Any = None,
                 pubsub: Any = None, cassandra: Any = None,
                 mongo: Any = None, clickhouse: Any = None,
                 oracle: Any = None, scylladb: Any = None,
                 logger: Any = None) -> None:
        self.sql = sql
        self.redis = redis
        self.kv = kv
        self.pubsub = pubsub
        self.cassandra = cassandra
        self.mongo = mongo
        self.clickhouse = clickhouse
        self.oracle = oracle
        self.scylladb = scylladb
        self.logger = logger


class MigrationError(Exception):
    pass


LEDGER_TABLE = "gofr_migrations"
LEDGER_PREFIX = "gofr_migrations:"


class _SQLMigrator:
    def __init__(self, sql: Any) -> None:
        self.sql = sql

    def ensure_ledger(self) -> None:
        self.sql.exec(
            f"CREATE TABLE IF NOT EXISTS {LEDGER_TABLE} ("
            "version INTEGER PRIMARY KEY, method TEXT NOT NULL, "
            "start_time TEXT NOT NULL, duration_ms INTEGER)")

    def last_version(self) -> int:
        row = self.sql.query_row(
            f"SELECT MAX(version) AS v FROM {LEDGER_TABLE}")
        return int(row["v"]) if row is not None and row["v"] is not None else 0

    def record(self, tx: Any, version: int, started: float) -> None:
        tx.exec(
            f"INSERT INTO {LEDGER_TABLE} "
            "(version, method, start_time, duration_ms) VALUES "
            f"({self.sql.ph(1)}, {self.sql.ph(2)}, {self.sql.ph(3)}, "
            f"{self.sql.ph(4)})",
            version, "UP",
            time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(started)),
            int((time.time() - started) * 1000))


class _KVStyleMigrator:
    """Redis- and KV-backed ledger: one key per version."""

    def __init__(self, store: Any) -> None:
        self.store = store

    def ensure_ledger(self) -> None:
        pass  # key space needs no DDL

    def last_version(self) -> int:
        try:
            keys = self.store.keys()
        except TypeError:  # redis-style keys(pattern)
            keys = self.store.keys(LEDGER_PREFIX + "*")
        versions = []
        for key in keys:
            if key.startswith(LEDGER_PREFIX):
                try:
                    versions.append(int(key[len(LEDGER_PREFIX):]))
                except ValueError:
                    continue
        return max(versions, default=0)

    def record(self, version: int, started: float) -> None:
        self.store.set(f"{LEDGER_PREFIX}{version}", json.dumps({
            "method": "UP",
            "start_time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime(started)),
            "duration_ms": int((time.time() - started) * 1000)}))


class _StatementMigrator:
    """Ledger for stores speaking ``exec(stmt, *args)`` /
    ``query(stmt, *args)`` with qmark placeholders — cassandra,
    scylladb, clickhouse, oracle (reference builds one migrator per
    initialized datasource, each with its own ledger:
    migration/cassandra.go, clickhouse.go, migration.go:137-235).

    ``ddls`` is tried in order: the store's native dialect first
    (e.g. ClickHouse's MergeTree engine clause), then a generic
    fallback for embedded/mini engines."""

    def __init__(self, store: Any, ddls: tuple[str, ...]) -> None:
        self.store = store
        self.ddls = ddls

    def ensure_ledger(self) -> None:
        try:  # already there?
            self.store.query(
                f"SELECT version FROM {LEDGER_TABLE} WHERE version < 0")
            return
        except Exception:
            pass
        last_exc: Exception | None = None
        for ddl in self.ddls:
            try:
                self.store.exec(ddl)
                return
            except Exception as exc:  # try the next dialect
                last_exc = exc
        raise MigrationError(
            f"cannot create migration ledger: {last_exc}")

    def last_version(self) -> int:
        rows = self.store.query(f"SELECT version FROM {LEDGER_TABLE}")
        versions = []
        for row in rows:
            value = row.get("version") if hasattr(row, "get") else None
            if value is None and hasattr(row, "get"):
                value = row.get("VERSION")
            if value is not None:
                versions.append(int(value))
        return max(versions, default=0)

    def record(self, version: int, started: float) -> None:
        self.store.exec(
            f"INSERT INTO {LEDGER_TABLE} "
            "(version, method, start_time, duration_ms) "
            "VALUES (?, ?, ?, ?)",
            version, "UP",
            time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(started)),
            int((time.time() - started) * 1000))


_CQL_LEDGER_DDLS = (
    f"CREATE TABLE IF NOT EXISTS {LEDGER_TABLE} ("
    "version BIGINT PRIMARY KEY, method TEXT, "
    "start_time TEXT, duration_ms BIGINT)",
)
_CLICKHOUSE_LEDGER_DDLS = (
    f"CREATE TABLE IF NOT EXISTS {LEDGER_TABLE} ("
    "version Int64, method String, start_time String, "
    "duration_ms Int64) ENGINE = MergeTree ORDER BY version",
    # embedded/mini engines reject the ENGINE clause
    f"CREATE TABLE IF NOT EXISTS {LEDGER_TABLE} ("
    "version BIGINT PRIMARY KEY, method TEXT, "
    "start_time TEXT, duration_ms BIGINT)",
)
_ORACLE_LEDGER_DDLS = (
    # oracle has no IF NOT EXISTS; ensure_ledger probes first, and an
    # 'already exists' race still lands in the generic fallback's error
    f"CREATE TABLE {LEDGER_TABLE} ("
    "version NUMBER PRIMARY KEY, method VARCHAR2(8), "
    "start_time VARCHAR2(32), duration_ms NUMBER)",
    f"CREATE TABLE IF NOT EXISTS {LEDGER_TABLE} ("
    "version BIGINT PRIMARY KEY, method TEXT, "
    "start_time TEXT, duration_ms BIGINT)",
)


class _MongoMigrator:
    """Document ledger: one doc per version in a ``gofr_migrations``
    collection (reference migration/mongo.go)."""

    def __init__(self, store: Any) -> None:
        self.store = store

    def ensure_ledger(self) -> None:
        pass  # collections need no DDL

    def last_version(self) -> int:
        docs = self.store.find(LEDGER_TABLE)
        return max((int(d["version"]) for d in docs if "version" in d),
                   default=0)

    def record(self, version: int, started: float) -> None:
        self.store.insert_one(LEDGER_TABLE, {
            "version": version, "method": "UP",
            "start_time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime(started)),
            "duration_ms": int((time.time() - started) * 1000)})


def run(container: Any, migrations: dict[int, Any]) -> list[int]:
    """Apply pending migrations; returns the versions that ran
    (reference migration.Run, migration.go:29-99)."""
    logger = container.logger
    if not migrations:
        return []
    for version, migration in migrations.items():
        if not isinstance(version, int) or version <= 0:
            raise MigrationError(f"invalid migration version {version!r}")
        if not callable(getattr(migration, "up", None)):
            raise MigrationError(f"migration {version} has no callable 'up'")

    sql_migrator = _SQLMigrator(container.sql) if container.sql else None
    side_migrators: list[Any] = [
        _KVStyleMigrator(store)
        for store in (container.redis, container.kv) if store]
    for slot, ddls in (("cassandra", _CQL_LEDGER_DDLS),
                       ("scylladb", _CQL_LEDGER_DDLS),
                       ("clickhouse", _CLICKHOUSE_LEDGER_DDLS),
                       ("oracle", _ORACLE_LEDGER_DDLS)):
        store = getattr(container, slot, None)
        if store is not None:
            side_migrators.append(_StatementMigrator(store, ddls))
    if getattr(container, "mongo", None) is not None:
        side_migrators.append(_MongoMigrator(container.mongo))
    if sql_migrator is None and not side_migrators:
        raise MigrationError(
            "no datasource initialized to track migrations against")

    if sql_migrator:
        sql_migrator.ensure_ledger()
    for migrator in side_migrators:
        migrator.ensure_ledger()
    lasts = ([sql_migrator.last_version()] if sql_migrator else []) + \
        [m.last_version() for m in side_migrators]
    last = max(lasts)

    def facade(sql_handle: Any) -> Datasource:
        return Datasource(sql=sql_handle, redis=container.redis,
                          kv=container.kv, pubsub=container.pubsub,
                          cassandra=getattr(container, "cassandra", None),
                          mongo=getattr(container, "mongo", None),
                          clickhouse=getattr(container, "clickhouse", None),
                          oracle=getattr(container, "oracle", None),
                          scylladb=getattr(container, "scylladb", None),
                          logger=logger)

    applied: list[int] = []
    for version in sorted(migrations):
        if version <= last:
            continue
        started = time.time()
        migration = migrations[version]
        if sql_migrator is not None:
            # transactional: the migration's SQL rides the tx and rolls
            # back with the ledger row on failure (migration.go:68-97);
            # the other stores have no cross-statement transactions —
            # their ledger records only land after up() succeeds
            with container.sql.begin() as tx:
                ds = facade(tx)
                migration.up(ds)
                sql_migrator.record(tx, version, started)
        else:
            migration.up(facade(None))
        for migrator in side_migrators:
            migrator.record(version, started)
        applied.append(version)
        logger.info(f"migration {version} applied in "
                    f"{int((time.time() - started) * 1000)}ms")
    return applied
