"""Versioned datasource migrations with per-store ledgers."""

from .runner import Datasource, Migrate, MigrationError, run

__all__ = ["Migrate", "Datasource", "MigrationError", "run"]
