"""Asyncio gRPC server with the framework's observability chain.

The role of reference pkg/gofr/grpc.go: a gRPC transport sharing the
HTTP server's observability — every RPC gets panic recovery, a span
(propagated from ``traceparent`` metadata), a structured log line, and
an ``app_grpc_server_duration`` histogram (grpc.go:96-119,
grpc/log.go:150-284). Services are ``GRPCService`` subclasses with
container injection at registration (grpc.go:222-269); the standard
``grpc.health.v1.Health`` service is registered automatically, backed
by the container's aggregate health (health_gofr.go:21-34).

Runs on ``grpc.aio`` so server-streaming RPCs can consume the serving
engine's async token streams directly — no thread hops on the token
path.
"""

from __future__ import annotations

import asyncio
import time
import traceback
from typing import Any, AsyncIterator, Mapping

import grpc

from ..context import Context
from .health import (
    NOT_SERVING,
    SERVING,
    HealthState,
    decode_check_request,
    encode_check_response,
)
from .service import (
    BIDI_STREAM,
    CLIENT_STREAM,
    SERVER_STREAM,
    UNARY,
    GRPCService,
    RPCSpec,
)

DEFAULT_GRPC_PORT = 9000

# 5ms-10s, the reference's gRPC latency buckets (health_gofr.go:42-44)
_GRPC_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10)


class GRPCRequest:
    """Request implementation for RPC handlers: ``bind`` returns the
    decoded request; ``param`` reads invocation metadata."""

    def __init__(self, payload: Any, metadata: Mapping[str, str],
                 method: str) -> None:
        self.payload = payload
        self.metadata = dict(metadata)
        self.method = method

    def bind(self, target: Any = None) -> Any:
        if target is not None and isinstance(self.payload, Mapping) \
                and isinstance(target, type):
            import dataclasses
            if dataclasses.is_dataclass(target):
                from ..http.request import bind_dataclass
                return bind_dataclass(self.payload, target)
        return self.payload

    def param(self, key: str) -> str:
        return self.metadata.get(key.lower(), "")

    def params(self, key: str) -> list[str]:
        value = self.param(key)
        return value.split(",") if value else []

    def path_param(self, key: str) -> str:
        return ""

    def host_name(self) -> str:
        return self.metadata.get(":authority", "")

    def header(self, key: str) -> str:
        return self.param(key)


class GRPCServer:
    def __init__(self, container: Any, *, port: int = DEFAULT_GRPC_PORT,
                 logger: Any = None) -> None:
        self.container = container
        self.port = port
        self.logger = logger if logger is not None else container.logger
        self.health = HealthState()
        self._services: list[GRPCService] = []
        self._server: grpc.aio.Server | None = None
        self.bound_port: int = port
        from .reflection import DescriptorRegistry
        self._descriptors = DescriptorRegistry()
        container.metrics.new_histogram(
            "app_grpc_server_duration", "gRPC server handle time in seconds",
            buckets=_GRPC_BUCKETS)

    # ------------------------------------------------------- registration
    def register(self, service: GRPCService) -> None:
        """Inject the container and queue the service
        (reference grpc.go:200-269 RegisterService)."""
        if not service.name:
            raise ValueError(
                f"{type(service).__name__}.name must be the fully-qualified "
                "gRPC service name")
        service.container = self.container
        self._services.append(service)
        self.health.set(service.name, SERVING)

    def register_descriptors(self, fds: bytes) -> None:
        """Feed a protoc-compiled FileDescriptorSet (protogen's
        FILE_DESCRIPTOR_SET) to the reflection surface."""
        self._descriptors.add_serialized_set(fds)

    # ------------------------------------------------------ observability
    def _observed(self, service: GRPCService, spec: RPCSpec):
        """recovery + span + log + metrics around one RPC
        (reference grpc/log.go:150-284)."""
        full_method = f"/{service.name}/{spec.name}"
        tracer = self.container.tracer
        metrics = self.container.metrics
        logger = self.logger

        def observe(start: float, status: str) -> None:
            duration = time.perf_counter() - start
            metrics.record_histogram("app_grpc_server_duration", duration,
                                     method=full_method, status=status)
            record = {"method": full_method, "status": status,
                      "duration_us": int(duration * 1e6), "kind": "grpc"}
            (logger.info if status == "OK" else logger.error)(record)

        def make_ctx(payload: Any, grpc_ctx) -> Context:
            metadata = {k: v for k, v in (grpc_ctx.invocation_metadata() or ())}
            ctx = Context(request=GRPCRequest(payload, metadata, full_method),
                          container=self.container)
            return ctx, metadata

        async def recover(exc: Exception, start: float, grpc_ctx) -> None:
            # recovery interceptor (grpc.go:98); handlers pick their
            # status by setting exc.grpc_status, default INTERNAL
            code = getattr(exc, "grpc_status", grpc.StatusCode.INTERNAL)
            logger.error(f"grpc panic in {full_method}: {exc!r}",
                         stack=traceback.format_exc())
            observe(start, code.name)
            await grpc_ctx.abort(code, str(exc) or "internal error")

        async def call_unary(request_bytes_decoded, grpc_ctx):
            start = time.perf_counter()
            ctx, metadata = make_ctx(request_bytes_decoded, grpc_ctx)
            span = tracer.start_span(full_method,
                                     traceparent=metadata.get("traceparent"))
            try:
                result = spec.fn(service, ctx, request_bytes_decoded)
                if hasattr(result, "__await__"):
                    result = await result
                observe(start, "OK")
                return result
            except asyncio.CancelledError:
                observe(start, "CANCELLED")
                raise
            except Exception as exc:
                await recover(exc, start, grpc_ctx)
            finally:
                span.end()

        async def call_stream(request_decoded, grpc_ctx):
            start = time.perf_counter()
            ctx, metadata = make_ctx(request_decoded, grpc_ctx)
            span = tracer.start_span(full_method,
                                     traceparent=metadata.get("traceparent"))
            try:
                async for item in spec.fn(service, ctx, request_decoded):
                    yield item
                observe(start, "OK")
            except asyncio.CancelledError:
                observe(start, "CANCELLED")
                raise
            except Exception as exc:
                await recover(exc, start, grpc_ctx)
            finally:
                span.end()

        return call_unary if spec.kind in (UNARY, CLIENT_STREAM) \
            else call_stream

    def _handler_for(self, service: GRPCService, spec: RPCSpec):
        behavior = self._observed(service, spec)
        kw = {"request_deserializer": spec.request_deserializer,
              "response_serializer": spec.response_serializer}
        if spec.kind == UNARY:
            return grpc.unary_unary_rpc_method_handler(behavior, **kw)
        if spec.kind == SERVER_STREAM:
            return grpc.unary_stream_rpc_method_handler(behavior, **kw)
        if spec.kind == CLIENT_STREAM:
            return grpc.stream_unary_rpc_method_handler(behavior, **kw)
        return grpc.stream_stream_rpc_method_handler(behavior, **kw)

    # ------------------------------------------------------------- health
    def _health_handlers(self):
        state = self.health
        container = self.container

        def overall() -> int:
            try:
                return SERVING if container.health()["status"] != "DOWN" \
                    else NOT_SERVING
            except Exception:
                return NOT_SERVING

        async def check(service_name: str, grpc_ctx) -> int:
            if service_name == "":
                return overall()
            return state.check(service_name)

        async def watch(service_name: str, grpc_ctx) -> AsyncIterator[int]:
            yield await check(service_name, grpc_ctx)
            # hold the stream open; new statuses are pushed on change in
            # richer implementations — polling keeps this simple
            while not grpc_ctx.cancelled():
                await asyncio.sleep(1.0)
                yield await check(service_name, grpc_ctx)

        kw = {"request_deserializer": decode_check_request,
              "response_serializer": encode_check_response}
        return grpc.method_handlers_generic_handler(
            "grpc.health.v1.Health",
            {"Check": grpc.unary_unary_rpc_method_handler(check, **kw),
             "Watch": grpc.unary_stream_rpc_method_handler(watch, **kw)})

    # ---------------------------------------------------------- lifecycle
    async def start(self) -> None:
        self._server = grpc.aio.server()
        for service in self._services:
            handlers = {spec.name: self._handler_for(service, spec)
                        for spec in service.rpc_specs()}
            self._server.add_generic_rpc_handlers(
                (grpc.method_handlers_generic_handler(service.name,
                                                      handlers),))
        self._server.add_generic_rpc_handlers((self._health_handlers(),))
        # reflection, gated exactly as the reference gates it
        # (GRPC_ENABLE_REFLECTION, reference grpc.go:130-134)
        enabled = "false"
        config = getattr(self.container, "config", None)
        if config is not None:
            enabled = config.get_or_default("GRPC_ENABLE_REFLECTION",
                                            "false").lower()
        if enabled == "true":
            from .reflection import reflection_handler
            names = [s.name for s in self._services] + [
                "grpc.health.v1.Health",
                "grpc.reflection.v1alpha.ServerReflection",
                "grpc.reflection.v1.ServerReflection"]
            self._server.add_generic_rpc_handlers(
                tuple(reflection_handler(lambda: sorted(names),
                                         registry=self._descriptors)))
        self.bound_port = self._server.add_insecure_port(
            f"0.0.0.0:{self.port}")
        if self.bound_port == 0 and self.port != 0:
            # grpc.aio reports bind failure as port 0, not an OSError —
            # same friendly guard as the HTTP listeners
            message = (f"port {self.port} is already in use (or cannot "
                       f"bind); set GRPC_PORT to a free port")
            self.logger.error(message)
            raise RuntimeError(message)
        await self._server.start()
        self.logger.info(f"gRPC server listening on 0.0.0.0:{self.bound_port}")

    async def shutdown(self, grace: float = 5.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None
