""".proto → service skeleton generator (the gofr-cli analog).

The reference ships protoc-generated ``*_gofr.go`` glue (SURVEY §2.8;
examples/grpc/grpc-unary-server/server/hello_gofr.go:24-60) produced by
``gofr wrap grpc``. This module is that tool for the framework's
decorator-based gRPC surface:

    python -m gofr_tpu.grpc.protogen chat.proto -o chat_gofr.py

generates, from the ``.proto`` alone:

- a ``@dataclass`` per message (the JSON-codec request/response shape;
  protoc-generated clients still interop through the server's proto
  codec path when message classes are supplied),
- a ``<Service>Base(GRPCService)`` skeleton per service — one
  ``@rpc`` / ``@server_stream_rpc`` / ``@client_stream_rpc`` /
  ``@bidi_stream_rpc`` method per RPC, raising NotImplementedError
  until filled in,
- a ``<Service>Client`` over ``grpc.aio`` with the matching method
  kinds, and
- when ``protoc`` is on PATH, the compiled ``FileDescriptorSet`` bytes
  (``FILE_DESCRIPTOR_SET``) — ``app.register_grpc_service`` picks the
  constant up from the generated module automatically (or feed it to
  ``GRPCServer.register_descriptors`` directly), after which server
  reflection answers ``file_containing_symbol`` with real descriptors
  instead of NOT_FOUND, so ``grpcurl`` works schema-aware.

The parser handles the proto3 subset service definitions use: package,
messages (scalar/repeated/map/nested-reference fields), services with
unary and streaming RPCs, comments, and options (ignored). It is a
generator's front-end, not a validator — protoc remains the authority
when present.
"""

from __future__ import annotations

import re
import shutil
import subprocess
import tempfile
from dataclasses import dataclass, field
from pathlib import Path

# ----------------------------------------------------------------- model


@dataclass
class ProtoField:
    name: str
    type: str
    repeated: bool = False
    number: int = 0


@dataclass
class ProtoMessage:
    name: str
    fields: list[ProtoField] = field(default_factory=list)


@dataclass
class ProtoRPC:
    name: str
    request: str
    response: str
    client_stream: bool = False
    server_stream: bool = False


@dataclass
class ProtoService:
    name: str
    rpcs: list[ProtoRPC] = field(default_factory=list)


@dataclass
class ProtoFile:
    package: str = ""
    messages: list[ProtoMessage] = field(default_factory=list)
    services: list[ProtoService] = field(default_factory=list)


# ---------------------------------------------------------------- parser

_COMMENT = re.compile(r"//[^\n]*|/\*.*?\*/", re.S)
_PACKAGE = re.compile(r"\bpackage\s+([\w.]+)\s*;")
_MESSAGE = re.compile(r"\bmessage\s+(\w+)\s*\{")
_SERVICE = re.compile(r"\bservice\s+(\w+)\s*\{")
_RPC = re.compile(
    r"\brpc\s+(\w+)\s*\(\s*(stream\s+)?([\w.]+)\s*\)\s*"
    r"returns\s*\(\s*(stream\s+)?([\w.]+)\s*\)")
# applied per ';'-separated statement, not per line — proto bodies are
# whitespace-agnostic (`message Pet { string name = 1; int32 age = 2; }`)
_FIELD = re.compile(
    r"\s*(repeated\s+|optional\s+)?([\w.<>, ]+?)\s+(\w+)\s*=\s*(\d+)"
    r"\s*(?:\[[^\]]*\])?\s*$")


def _block(text: str, open_brace: int) -> tuple[str, int]:
    """Return the brace-balanced body starting after ``open_brace``."""
    depth = 1
    i = open_brace + 1
    while i < len(text) and depth:
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
        i += 1
    return text[open_brace + 1:i - 1], i


def parse_proto(source: str) -> ProtoFile:
    text = _COMMENT.sub("", source)
    out = ProtoFile()
    m = _PACKAGE.search(text)
    if m:
        out.package = m.group(1)

    for m in _MESSAGE.finditer(text):
        body, _end = _block(text, m.end() - 1)
        msg = ProtoMessage(name=m.group(1))
        # nested messages are parsed as their own (flattened) entries;
        # strip their bodies so their fields don't leak into the parent
        flat = body
        for nm in _MESSAGE.finditer(body):
            nested_body, nested_end = _block(body, nm.end() - 1)
            flat = flat.replace(body[nm.start():nested_end], "")
        for stmt in flat.split(";"):
            f = _FIELD.match(stmt)
            if f is None:
                continue
            modifier, ftype, fname, num = f.groups()
            if ftype.split()[0] in ("option", "reserved", "oneof",
                                    "enum", "message", "rpc", "returns"):
                continue
            msg.fields.append(ProtoField(
                name=fname, type=ftype.strip(),
                repeated=(modifier or "").strip() == "repeated",
                number=int(num)))
        out.messages.append(msg)

    for m in _SERVICE.finditer(text):
        body, _end = _block(text, m.end() - 1)
        svc = ProtoService(name=m.group(1))
        for r in _RPC.finditer(body):
            name, req_stream, req, resp_stream, resp = r.groups()
            svc.rpcs.append(ProtoRPC(
                name=name, request=req.split(".")[-1],
                response=resp.split(".")[-1],
                client_stream=bool(req_stream),
                server_stream=bool(resp_stream)))
        out.services.append(svc)
    return out


# ------------------------------------------------------------- generator

_PY_TYPES = {
    "double": "float", "float": "float", "int32": "int", "int64": "int",
    "uint32": "int", "uint64": "int", "sint32": "int", "sint64": "int",
    "fixed32": "int", "fixed64": "int", "sfixed32": "int",
    "sfixed64": "int", "bool": "bool", "string": "str", "bytes": "bytes",
}


def _py_type(f: ProtoField, known: set[str]) -> tuple[str, str]:
    """-> (annotation, default expr)."""
    if f.type.startswith("map<"):
        return "dict", "field(default_factory=dict)"
    base = _PY_TYPES.get(f.type)
    if base is None:
        base = f'"{f.type}"' if f.type in known else "dict"
    if f.repeated:
        return "list", "field(default_factory=list)"
    defaults = {"float": "0.0", "int": "0", "bool": "False",
                "str": '""', "bytes": 'b""', "dict": "None"}
    return base, defaults.get(base, "None")


def _descriptor_set(proto_path: Path) -> bytes | None:
    """Compile with protoc when available — real descriptors make the
    reflection surface schema-aware."""
    protoc = shutil.which("protoc")
    if protoc is None:
        return None
    with tempfile.TemporaryDirectory() as tmp:
        out = Path(tmp) / "fds.bin"
        try:
            proc = subprocess.run(
                [protoc, f"-I{proto_path.parent}", str(proto_path),
                 "--include_imports", f"--descriptor_set_out={out}"],
                capture_output=True, text=True, timeout=60)
        except subprocess.TimeoutExpired:
            return None  # degrade like every other protoc failure
        if proc.returncode != 0:
            return None
        return out.read_bytes()


_KIND_DECOR = {
    (False, False): "rpc",
    (False, True): "server_stream_rpc",
    (True, False): "client_stream_rpc",
    (True, True): "bidi_stream_rpc",
}


def generate(proto_path: str | Path) -> str:
    proto_path = Path(proto_path)
    pf = parse_proto(proto_path.read_text())
    known = {m.name for m in pf.messages}
    lines: list[str] = [
        f'"""Generated from {proto_path.name} by gofr_tpu.grpc.protogen',
        "— the gofr-cli `wrap grpc` analog. Fill in the *Base methods.",
        '"""',
        "",
        "from __future__ import annotations",
        "",
        "from dataclasses import dataclass, field",
        "from typing import Any, AsyncIterator",
        "",
        "from gofr_tpu.grpc.service import (GRPCService, bidi_stream_rpc,",
        "                                   client_stream_rpc, rpc,",
        "                                   server_stream_rpc)",
        "",
    ]

    for msg in pf.messages:
        lines.append("@dataclass")
        lines.append(f"class {msg.name}:")
        if not msg.fields:
            lines.append("    pass")
        for f in msg.fields:
            ann, default = _py_type(f, known)
            lines.append(f"    {f.name}: {ann} = {default}")
        lines += [
            "",
            "    @classmethod",
            "    def from_dict(cls, d):",
            "        d = d if isinstance(d, dict) else {}",
            "        names = set(cls.__dataclass_fields__)",
            "        return cls(**{k: v for k, v in d.items()"
            " if k in names})",
            "", ""]

    for svc in pf.services:
        full = f"{pf.package}.{svc.name}" if pf.package else svc.name
        lines.append(f"class {svc.name}Base(GRPCService):")
        lines.append(f'    """Server skeleton for `{full}` — subclass'
                     " and implement each RPC.\"\"\"")
        lines.append("")
        lines.append(f'    name = "{full}"')
        for r in svc.rpcs:
            decor = _KIND_DECOR[(r.client_stream, r.server_stream)]
            lines.append("")
            lines.append(f"    @{decor}")
            if r.server_stream:
                lines.append(f"    async def {r.name}(self, ctx, request)"
                             " -> AsyncIterator[dict]:")
            else:
                lines.append(f"    async def {r.name}(self, ctx, request)"
                             " -> Any:")
            lines.append(f'        """rpc {r.name}('
                         f'{"stream " if r.client_stream else ""}'
                         f'{r.request}) returns ('
                         f'{"stream " if r.server_stream else ""}'
                         f'{r.response})"""')
            lines.append(f"        req = {r.request}.from_dict(request)"
                         if r.request in known else
                         "        req = request")
            lines.append("        raise NotImplementedError"
                         f'("implement {r.name}")')
            if r.server_stream:
                lines.append("        yield {}  # pragma: no cover")
        lines += ["", ""]

        lines.append(f"class {svc.name}Client:")
        lines.append(f'    """grpc.aio client for `{full}` '
                     '(JSON codec)."""')
        lines += [
            "",
            "    def __init__(self, channel):",
            "        import json as _json",
            "        self._channel = channel",
            "        self._dumps = lambda o: _json.dumps(",
            "            o.__dict__ if hasattr(o, '__dataclass_fields__')"
            " else o).encode()",
            "        self._loads = lambda b: _json.loads(b or b'{}')",
        ]
        for r in svc.rpcs:
            path = f"/{full}/{r.name}"
            if not r.client_stream and not r.server_stream:
                lines += [
                    "",
                    f"    async def {r.name}(self, request):",
                    f"        call = self._channel.unary_unary(",
                    f'            "{path}",',
                    "            request_serializer=self._dumps,",
                    "            response_deserializer=self._loads)",
                    "        return await call(request)",
                ]
            elif r.server_stream and not r.client_stream:
                lines += [
                    "",
                    f"    def {r.name}(self, request):",
                    f"        call = self._channel.unary_stream(",
                    f'            "{path}",',
                    "            request_serializer=self._dumps,",
                    "            response_deserializer=self._loads)",
                    "        return call(request)",
                ]
            elif r.client_stream and not r.server_stream:
                lines += [
                    "",
                    f"    async def {r.name}(self, request_iterator):",
                    f"        call = self._channel.stream_unary(",
                    f'            "{path}",',
                    "            request_serializer=self._dumps,",
                    "            response_deserializer=self._loads)",
                    "        return await call(request_iterator)",
                ]
            else:
                lines += [
                    "",
                    f"    def {r.name}(self, request_iterator):",
                    f"        call = self._channel.stream_stream(",
                    f'            "{path}",',
                    "            request_serializer=self._dumps,",
                    "            response_deserializer=self._loads)",
                    "        return call(request_iterator)",
                ]
        lines += ["", ""]

    fds = _descriptor_set(proto_path)
    if fds is not None:
        lines.append("#: protoc-compiled FileDescriptorSet — register"
                     " with the server so")
        lines.append("#: reflection answers file_containing_symbol"
                     " with real descriptors")
        lines.append(f"FILE_DESCRIPTOR_SET = {fds!r}")
    else:
        lines.append("FILE_DESCRIPTOR_SET = None  # protoc not on PATH"
                     " at generation time")
    lines.append("")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m gofr_tpu.grpc.protogen",
        description="Generate a gofr_tpu gRPC service skeleton "
                    "from a .proto file")
    ap.add_argument("proto", help="path to the .proto file")
    ap.add_argument("-o", "--out", help="output .py path "
                    "(default: <proto>_gofr.py)")
    args = ap.parse_args(argv)
    src = Path(args.proto)
    out = Path(args.out) if args.out else \
        src.with_name(src.stem + "_gofr.py")
    out.write_text(generate(src))
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
