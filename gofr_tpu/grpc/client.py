"""gRPC client helpers: JSON-codec calls against GRPCService servers,
plus standard health checks — the counterpart of the reference's
generated client glue (examples/grpc/grpc-unary-client)."""

from __future__ import annotations

from typing import Any, AsyncIterator

import grpc

from .health import decode_check_response, encode_check_request, status_name
from .service import _json_deserialize, _json_serialize


class GRPCClient:
    """Thin aio channel wrapper; one per target."""

    def __init__(self, target: str, *, tracer: Any = None) -> None:
        self.target = target
        self.tracer = tracer
        self._channel: grpc.aio.Channel | None = None

    def _chan(self) -> grpc.aio.Channel:
        if self._channel is None:
            self._channel = grpc.aio.insecure_channel(self.target)
        return self._channel

    def _metadata(self) -> list[tuple[str, str]]:
        if self.tracer is None:
            return []
        span = self.tracer.current_span()
        if span is None:
            return []
        return [("traceparent",
                 f"00-{span.trace_id}-{span.span_id}-01")]

    async def call(self, service: str, method: str, payload: Any = None, *,
                   timeout: float | None = None) -> Any:
        rpc = self._chan().unary_unary(
            f"/{service}/{method}",
            request_serializer=_json_serialize,
            response_deserializer=_json_deserialize)
        return await rpc(payload if payload is not None else {},
                         timeout=timeout, metadata=self._metadata())

    async def stream(self, service: str, method: str,
                     payload: Any = None) -> AsyncIterator[Any]:
        rpc = self._chan().unary_stream(
            f"/{service}/{method}",
            request_serializer=_json_serialize,
            response_deserializer=_json_deserialize)
        async for item in rpc(payload if payload is not None else {},
                              metadata=self._metadata()):
            yield item

    async def health_check(self, service: str = "") -> str:
        rpc = self._chan().unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=lambda s: encode_check_request(s),
            response_deserializer=decode_check_response)
        return status_name(await rpc(service))

    async def close(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
