"""gRPC transport: asyncio server with observability interceptors,
decorator-based services, standard health, and client helpers."""

from .client import GRPCClient
from .health import NOT_SERVING, SERVING, SERVICE_UNKNOWN
from .server import GRPCServer
from .service import (
    GRPCService,
    bidi_stream_rpc,
    client_stream_rpc,
    rpc,
    server_stream_rpc,
)

__all__ = ["GRPCServer", "GRPCClient", "GRPCService", "rpc",
           "server_stream_rpc", "client_stream_rpc", "bidi_stream_rpc",
           "SERVING", "NOT_SERVING", "SERVICE_UNKNOWN"]
