"""gRPC server reflection (grpc.reflection.v1alpha + v1), wire-
compatible with grpcurl/evans — hand-encoded protobuf like
:mod:`.health`, no grpc_reflection dependency.

Reference analog: ``reflection.Register(g.server)`` gated on
``GRPC_ENABLE_REFLECTION`` (reference pkg/gofr/grpc.go:130-134).

Supported reflection requests: ``list_services`` returns every
registered service (framework services + health + reflection itself);
the descriptor-oriented requests (``file_containing_symbol`` etc.)
answer ``NOT_FOUND`` — framework services declare JSON codecs in
Python, so there are no compiled ``.proto`` descriptors to serve, and
grpcurl falls back cleanly.

Wire shapes used (v1alpha and v1 are field-identical):
  ServerReflectionRequest  { string host = 1; oneof message_request {
      string file_by_filename = 3; string file_containing_symbol = 4;
      ... string list_services = 7; } }
  ServerReflectionResponse { string valid_host = 1;
      ServerReflectionRequest original_request = 2;
      oneof message_response {
        ListServiceResponse list_services_response = 6;
        ErrorResponse error_response = 7; } }
  ListServiceResponse { repeated ServiceResponse service = 1; }
  ServiceResponse { string name = 1; }
  ErrorResponse { int32 error_code = 1; string error_message = 2; }
"""

from __future__ import annotations

from typing import AsyncIterator, Callable, Iterable

import grpc

from .health import _decode_varint, _encode_varint

NOT_FOUND = 5           # grpc.StatusCode.NOT_FOUND.value[0]
UNIMPLEMENTED = 12

#: request fields that carry the oneof discriminator
_REQUEST_FIELDS = {3: "file_by_filename", 4: "file_containing_symbol",
                   5: "file_containing_extension",
                   6: "all_extension_numbers_of_type", 7: "list_services"}


def decode_reflection_request(data: bytes) -> tuple[str, bytes, str]:
    """-> (oneof field name, raw request bytes, argument string)."""
    pos = 0
    which, arg = "", ""
    while pos < len(data):
        tag, pos = _decode_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2:
            length, pos = _decode_varint(data, pos)
            value = data[pos:pos + length]
            pos += length
            if field in _REQUEST_FIELDS:
                which = _REQUEST_FIELDS[field]
                arg = value.decode("utf-8", "replace")
        elif wire == 0:
            _, pos = _decode_varint(data, pos)
        else:
            break
    return which, data, arg


def _field(num: int, payload: bytes) -> bytes:
    return _encode_varint((num << 3) | 2) + _encode_varint(len(payload)) \
        + payload


def encode_list_services_response(request: bytes,
                                  names: Iterable[str]) -> bytes:
    services = b"".join(
        _field(1, _field(1, name.encode())) for name in names)
    return _field(2, request) + _field(6, services)


def encode_error_response(request: bytes, code: int, message: str) -> bytes:
    err = (_encode_varint(1 << 3) + _encode_varint(code)
           + _field(2, message.encode()))
    return _field(2, request) + _field(7, err)


class DescriptorRegistry:
    """Serialized ``FileDescriptorProto`` store keyed by file name and
    symbol, fed from protoc-compiled ``FileDescriptorSet`` bytes (the
    ``FILE_DESCRIPTOR_SET`` constant :mod:`.protogen` emits). With one
    registered, reflection answers descriptor requests for real —
    grpcurl becomes schema-aware instead of falling back."""

    def __init__(self) -> None:
        self._files: dict[str, bytes] = {}        # file name -> fdp bytes
        self._deps: dict[str, list[str]] = {}
        self._symbols: dict[str, str] = {}         # symbol -> file name

    @staticmethod
    def _fields(blob: bytes):
        pos = 0
        while pos < len(blob):
            tag, pos = _decode_varint(blob, pos)
            num, wire = tag >> 3, tag & 7
            if wire == 2:
                length, pos = _decode_varint(blob, pos)
                yield num, blob[pos:pos + length]
                pos += length
            elif wire == 0:
                value, pos = _decode_varint(blob, pos)
                yield num, value
            else:  # 64/32-bit fields don't appear in descriptors we read
                return

    def add_serialized_set(self, fds: bytes) -> None:
        for num, value in self._fields(fds):
            if num == 1 and isinstance(value, bytes):
                self._add_file(value)

    def _message_symbols(self, desc: bytes) -> list[str]:
        """DescriptorProto -> its name plus dotted nested message/enum
        names (field 3 nested_type, field 4 enum_type), recursively —
        `grpcurl describe pkg.Outer.Inner` must resolve."""
        own = ""
        nested: list[str] = []
        for num, value in self._fields(desc):
            if not isinstance(value, bytes):
                continue
            if num == 1:
                own = value.decode()
            elif num == 3:
                nested.extend(self._message_symbols(value))
            elif num == 4:  # EnumDescriptorProto: name is field 1 too
                for n2, v2 in self._fields(value):
                    if n2 == 1 and isinstance(v2, bytes):
                        nested.append(v2.decode())
        if not own:
            return []
        return [own] + [f"{own}.{n}" for n in nested]

    def _add_file(self, fdp: bytes) -> None:
        name, package = "", ""
        deps: list[str] = []
        symbols: list[str] = []
        for num, value in self._fields(fdp):
            if not isinstance(value, bytes):
                continue
            if num == 1:
                name = value.decode()
            elif num == 2:
                package = value.decode()
            elif num == 3:
                deps.append(value.decode())
            elif num in (4, 5):      # message_type / top-level enum
                symbols.extend(self._message_symbols(value))
            elif num == 6:           # service + its methods
                inner_name = ""
                methods: list[str] = []
                for n2, v2 in self._fields(value):
                    if n2 == 1 and isinstance(v2, bytes):
                        inner_name = v2.decode()
                    elif n2 == 2 and isinstance(v2, bytes):
                        for n3, v3 in self._fields(v2):
                            if n3 == 1 and isinstance(v3, bytes):
                                methods.append(v3.decode())
                if inner_name:
                    symbols.append(inner_name)
                    symbols.extend(f"{inner_name}.{m}" for m in methods)
        self._files[name] = fdp
        self._deps[name] = deps
        prefix = f"{package}." if package else ""
        for sym in symbols:
            self._symbols[prefix + sym] = name

    def _with_deps(self, name: str) -> list[bytes]:
        out: list[bytes] = []
        seen: set[str] = set()
        stack = [name]
        while stack:
            n = stack.pop()
            if n in seen or n not in self._files:
                continue
            seen.add(n)
            out.append(self._files[n])
            stack.extend(self._deps.get(n, []))
        return out

    def file_by_filename(self, filename: str) -> list[bytes] | None:
        return self._with_deps(filename) if filename in self._files \
            else None

    def file_containing_symbol(self, symbol: str) -> list[bytes] | None:
        name = self._symbols.get(symbol)
        return self._with_deps(name) if name is not None else None


def encode_file_descriptor_response(request: bytes,
                                    fdps: list[bytes]) -> bytes:
    # FileDescriptorResponse { repeated bytes file_descriptor_proto = 1 }
    # in ServerReflectionResponse oneof field 4
    payload = b"".join(_field(1, f) for f in fdps)
    return _field(2, request) + _field(4, payload)


def reflection_handler(service_names: Callable[[], list[str]],
                       registry: DescriptorRegistry | None = None):
    """Generic handlers for both reflection service versions."""

    async def info(request_iter, grpc_ctx) -> AsyncIterator[bytes]:
        async for raw in request_iter:
            which, original, arg = decode_reflection_request(raw)
            if which == "list_services":
                yield encode_list_services_response(original,
                                                    service_names())
            elif which in ("file_by_filename", "file_containing_symbol",
                           "file_containing_extension"):
                fdps = None
                if registry is not None:
                    if which == "file_by_filename":
                        fdps = registry.file_by_filename(arg)
                    elif which == "file_containing_symbol":
                        fdps = registry.file_containing_symbol(arg)
                if fdps:
                    yield encode_file_descriptor_response(original, fdps)
                else:
                    yield encode_error_response(
                        original, NOT_FOUND,
                        "no descriptor registered for that symbol"
                        if registry is not None else
                        "JSON-codec services carry no proto descriptors")
            else:
                yield encode_error_response(original, UNIMPLEMENTED,
                                            f"unsupported: {which or '?'}")

    handler = grpc.stream_stream_rpc_method_handler(
        info, request_deserializer=lambda b: b,
        response_serializer=lambda b: b)
    return [grpc.method_handlers_generic_handler(
        name, {"ServerReflectionInfo": handler})
        for name in ("grpc.reflection.v1alpha.ServerReflection",
                     "grpc.reflection.v1.ServerReflection")]
