"""gRPC server reflection (grpc.reflection.v1alpha + v1), wire-
compatible with grpcurl/evans — hand-encoded protobuf like
:mod:`.health`, no grpc_reflection dependency.

Reference analog: ``reflection.Register(g.server)`` gated on
``GRPC_ENABLE_REFLECTION`` (reference pkg/gofr/grpc.go:130-134).

Supported reflection requests: ``list_services`` returns every
registered service (framework services + health + reflection itself);
the descriptor-oriented requests (``file_containing_symbol`` etc.)
answer ``NOT_FOUND`` — framework services declare JSON codecs in
Python, so there are no compiled ``.proto`` descriptors to serve, and
grpcurl falls back cleanly.

Wire shapes used (v1alpha and v1 are field-identical):
  ServerReflectionRequest  { string host = 1; oneof message_request {
      string file_by_filename = 3; string file_containing_symbol = 4;
      ... string list_services = 7; } }
  ServerReflectionResponse { string valid_host = 1;
      ServerReflectionRequest original_request = 2;
      oneof message_response {
        ListServiceResponse list_services_response = 6;
        ErrorResponse error_response = 7; } }
  ListServiceResponse { repeated ServiceResponse service = 1; }
  ServiceResponse { string name = 1; }
  ErrorResponse { int32 error_code = 1; string error_message = 2; }
"""

from __future__ import annotations

from typing import AsyncIterator, Callable, Iterable

import grpc

from .health import _decode_varint, _encode_varint

NOT_FOUND = 5           # grpc.StatusCode.NOT_FOUND.value[0]
UNIMPLEMENTED = 12

#: request fields that carry the oneof discriminator
_REQUEST_FIELDS = {3: "file_by_filename", 4: "file_containing_symbol",
                   5: "file_containing_extension",
                   6: "all_extension_numbers_of_type", 7: "list_services"}


def decode_reflection_request(data: bytes) -> tuple[str, bytes, str]:
    """-> (oneof field name, raw request bytes, argument string)."""
    pos = 0
    which, arg = "", ""
    while pos < len(data):
        tag, pos = _decode_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2:
            length, pos = _decode_varint(data, pos)
            value = data[pos:pos + length]
            pos += length
            if field in _REQUEST_FIELDS:
                which = _REQUEST_FIELDS[field]
                arg = value.decode("utf-8", "replace")
        elif wire == 0:
            _, pos = _decode_varint(data, pos)
        else:
            break
    return which, data, arg


def _field(num: int, payload: bytes) -> bytes:
    return _encode_varint((num << 3) | 2) + _encode_varint(len(payload)) \
        + payload


def encode_list_services_response(request: bytes,
                                  names: Iterable[str]) -> bytes:
    services = b"".join(
        _field(1, _field(1, name.encode())) for name in names)
    return _field(2, request) + _field(6, services)


def encode_error_response(request: bytes, code: int, message: str) -> bytes:
    err = (_encode_varint(1 << 3) + _encode_varint(code)
           + _field(2, message.encode()))
    return _field(2, request) + _field(7, err)


def reflection_handler(service_names: Callable[[], list[str]]):
    """Generic handlers for both reflection service versions."""

    async def info(request_iter, grpc_ctx) -> AsyncIterator[bytes]:
        async for raw in request_iter:
            which, original, _arg = decode_reflection_request(raw)
            if which == "list_services":
                yield encode_list_services_response(original,
                                                    service_names())
            elif which in ("file_by_filename", "file_containing_symbol",
                           "file_containing_extension"):
                yield encode_error_response(
                    original, NOT_FOUND,
                    "JSON-codec services carry no proto descriptors")
            else:
                yield encode_error_response(original, UNIMPLEMENTED,
                                            f"unsupported: {which or '?'}")

    handler = grpc.stream_stream_rpc_method_handler(
        info, request_deserializer=lambda b: b,
        response_serializer=lambda b: b)
    return [grpc.method_handlers_generic_handler(
        name, {"ServerReflectionInfo": handler})
        for name in ("grpc.reflection.v1alpha.ServerReflection",
                     "grpc.reflection.v1.ServerReflection")]
