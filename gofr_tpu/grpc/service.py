"""gRPC service model: decorator-registered RPCs on plain classes.

The reference generates `*_gofr.go` glue from protos with a CLI
(SURVEY §2.8); here the service surface is declared in Python — each
``@rpc`` method becomes a gRPC method handler with a codec. The default
codec is JSON (any gRPC client that sends JSON bytes interoperates);
passing protobuf message classes switches to standard proto wire
format, so protoc-generated clients work unchanged.

Handlers receive a gofr ``Context`` (container injected — the analog of
reference grpc.go:222-269 injectContainer) plus the decoded request.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, replace
from typing import Any, Callable

UNARY = "unary"
SERVER_STREAM = "server_stream"
CLIENT_STREAM = "client_stream"
BIDI_STREAM = "bidi_stream"


def _json_serialize(obj: Any) -> bytes:
    if isinstance(obj, (bytes, bytearray)):
        return bytes(obj)
    return json.dumps(obj).encode()


def _json_deserialize(data: bytes) -> Any:
    if not data:
        return {}
    try:
        return json.loads(data)
    except json.JSONDecodeError:
        return data


@dataclass
class RPCSpec:
    name: str
    kind: str
    fn: Callable
    request_deserializer: Callable[[bytes], Any]
    response_serializer: Callable[[Any], bytes]


def _make_codecs(request_type: Any, response_type: Any):
    """proto message classes -> proto codec; None -> JSON codec."""
    if request_type is not None and hasattr(request_type, "FromString"):
        deserializer = request_type.FromString
    elif request_type is not None and callable(request_type):
        deserializer = lambda b: request_type(_json_deserialize(b))
    else:
        deserializer = _json_deserialize
    if response_type is not None and hasattr(response_type, "SerializeToString"):
        serializer = lambda m: m.SerializeToString()
    else:
        serializer = _json_serialize
    return deserializer, serializer


def _decorate(kind: str):
    def factory(fn: Callable | None = None, *, request_type: Any = None,
                response_type: Any = None, name: str | None = None):
        def wrap(f: Callable) -> Callable:
            deserializer, serializer = _make_codecs(request_type,
                                                    response_type)
            f.__rpc_spec__ = RPCSpec(
                name=name or f.__name__, kind=kind, fn=f,
                request_deserializer=deserializer,
                response_serializer=serializer)
            return f
        return wrap(fn) if fn is not None else wrap
    return factory


rpc = _decorate(UNARY)
server_stream_rpc = _decorate(SERVER_STREAM)
client_stream_rpc = _decorate(CLIENT_STREAM)
bidi_stream_rpc = _decorate(BIDI_STREAM)


class GRPCService:
    """Base class: subclass, set ``name`` (the fully-qualified gRPC
    service name, e.g. ``chat.ChatService``), decorate methods."""

    name: str = ""

    # set at registration (reference grpc.go:222 container injection)
    container: Any = None

    @classmethod
    def rpc_specs(cls) -> list[RPCSpec]:
        specs = []
        for attr in dir(cls):
            member = getattr(cls, attr)
            spec = getattr(member, "__rpc_spec__", None)
            if spec is None:
                # a subclass overriding a decorated base method (the
                # protogen skeleton pattern) keeps the base's spec but
                # serves the OVERRIDING implementation
                for base in cls.__mro__[1:]:
                    base_spec = getattr(getattr(base, attr, None),
                                        "__rpc_spec__", None)
                    if base_spec is not None:
                        spec = replace(base_spec, fn=member)
                        break
            if spec is not None:
                specs.append(spec)
        return specs
