"""Standard gRPC health service (grpc.health.v1.Health), wire-compatible
with protoc-generated clients — the messages are tiny, so the protobuf
wire format is encoded by hand instead of depending on grpc_health.

Reference analog: the generated health service every gofr gRPC server
registers (examples/grpc/grpc-unary-server/server/health_gofr.go:21-34).

Wire shapes:
  HealthCheckRequest  { string service = 1; }
  HealthCheckResponse { enum ServingStatus status = 1; }
"""

from __future__ import annotations

SERVING = 1
NOT_SERVING = 2
SERVICE_UNKNOWN = 3

_STATUS_NAMES = {0: "UNKNOWN", 1: "SERVING", 2: "NOT_SERVING",
                 3: "SERVICE_UNKNOWN"}


def _encode_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _decode_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while pos < len(data):
        byte = data[pos]
        result |= (byte & 0x7F) << shift
        pos += 1
        if not byte & 0x80:
            return result, pos
        shift += 7
    raise ValueError("truncated varint")


def encode_check_request(service: str = "") -> bytes:
    if not service:
        return b""
    raw = service.encode()
    return b"\x0a" + _encode_varint(len(raw)) + raw


def decode_check_request(data: bytes) -> str:
    pos = 0
    while pos < len(data):
        tag, pos = _decode_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 2:
            length, pos = _decode_varint(data, pos)
            return data[pos:pos + length].decode("utf-8", "replace")
        # skip unknown field
        if wire == 0:
            _, pos = _decode_varint(data, pos)
        elif wire == 2:
            length, pos = _decode_varint(data, pos)
            pos += length
        else:
            break
    return ""


def encode_check_response(status: int) -> bytes:
    return b"\x08" + _encode_varint(status)


def decode_check_response(data: bytes) -> int:
    pos = 0
    while pos < len(data):
        tag, pos = _decode_varint(data, pos)
        if tag >> 3 == 1 and tag & 7 == 0:
            value, pos = _decode_varint(data, pos)
            return value
    return 0


def status_name(status: int) -> str:
    return _STATUS_NAMES.get(status, "UNKNOWN")


class HealthState:
    """Mutable serving-status registry; '' is the overall server."""

    def __init__(self) -> None:
        self._statuses: dict[str, int] = {"": SERVING}

    def set(self, service: str, status: int) -> None:
        self._statuses[service] = status

    def check(self, service: str) -> int:
        return self._statuses.get(service, SERVICE_UNKNOWN)
