"""Ragged paged decode-attention — pages read in place via block table.

The paged KV layout (:mod:`.paged_kv`) stores K/V in a HEAD-MAJOR page
pool ``[Hkv, Np, pg, hd]`` per layer with per-slot block tables. The
generic engine path materialises a dense per-slot view of the WHOLE
pool allocation every K-step pass (``gather_view``), which costs
O(full-cache) extra HBM traffic on top of attention's own reads —
vLLM's layout without vLLM's kernel (round-3 verdict weak #2).

This kernel removes the materialisation: each grid cell (slot b,
kv-head h) walks ONLY the pages covering ``lengths[b]`` rows (ragged —
shorter slots read fewer pages), DMA-ing pages HBM→VMEM double-buffered
and folding them into an online-softmax accumulator. The pool is never
reshaped, copied, or padded to the per-slot maximum.

Head-major matters on real hardware: Mosaic tiles the trailing two
dims of a memref, so slicing a TRAILING head axis to 1 per grid cell
(the r4 ``[Np, pg, Hkv, hd]`` layout) is illegal ("Slice shape along
dimension 2 must be aligned to tiling (8), but is 1" — first real-TPU
compile, r5), while ``pool.at[h, pid]`` slices only untiled leading
dims AND makes each page read a contiguous [pg, hd] block instead of a
strided one.

Layouts (decode, Sq == 1):
- ``q``        [B, Hq, hd]
- ``k_pool``   [Hkv, Np, pg, hd] (one layer's pool; bf16 in serving)
- ``tables``   [B, Mp] int32 — page ids, out-of-range = unallocated
- ``lengths``  [B] int32 — valid rows per slot (AFTER this step's write)
- out          [B, Hq, hd]

``paged_decode_attention`` dispatches: 'pallas' (TPU), 'interpret'
(kernel under the interpreter — CPU tests), 'xla' (gather fallback),
'auto' (pallas on TPU, xla elsewhere).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# kernel compiles on the installed toolchain either side of the rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

NEG_INF = -1e30


def _is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ------------------------------------------------------------------ kernel

def _paged_decode_kernel(tables_ref, lengths_ref, q_ref, k_hbm, v_hbm,
                         o_ref, k_buf, v_buf, acc_ref, m_ref, l_ref,
                         sems, *, page: int, pages_per_chunk: int,
                         max_pages: int, n_pages: int, scale: float):
    b = pl.program_id(0)
    h = pl.program_id(1)
    chunk = pages_per_chunk * page
    length = lengths_ref[b]
    n_chunks = jnp.maximum(pl.cdiv(length, chunk), 1)

    def start_chunk(ci, slot):
        # one DMA per page: pages are scattered in the pool, so a
        # chunk is pages_per_chunk independent copies — each a
        # CONTIGUOUS [page, hd] block in the head-major pool
        for j in range(pages_per_chunk):
            # tail chunks index past the table: clamp — their rows are
            # masked off by `length` below, they just must not fault
            page_idx = jnp.minimum(ci * pages_per_chunk + j,
                                   max_pages - 1)
            pid = jnp.minimum(tables_ref[b, page_idx], n_pages - 1)
            pltpu.make_async_copy(
                k_hbm.at[h, pid],
                k_buf.at[slot, pl.ds(j * page, page), :],
                sems.at[slot, 0, j]).start()
            pltpu.make_async_copy(
                v_hbm.at[h, pid],
                v_buf.at[slot, pl.ds(j * page, page), :],
                sems.at[slot, 1, j]).start()

    def wait_chunk(ci, slot):
        for j in range(pages_per_chunk):
            page_idx = jnp.minimum(ci * pages_per_chunk + j,
                                   max_pages - 1)
            pid = jnp.minimum(tables_ref[b, page_idx], n_pages - 1)
            pltpu.make_async_copy(
                k_hbm.at[h, pid],
                k_buf.at[slot, pl.ds(j * page, page), :],
                sems.at[slot, 0, j]).wait()
            pltpu.make_async_copy(
                v_hbm.at[h, pid],
                v_buf.at[slot, pl.ds(j * page, page), :],
                sems.at[slot, 1, j]).wait()

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    start_chunk(0, 0)
    qf = q_ref[0, 0].astype(jnp.float32) * scale        # [G, hd]

    def body(ci, _):
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < n_chunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        k = k_buf[slot].astype(jnp.float32)             # [chunk, hd]
        s = jax.lax.dot_general(
            qf, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [G, chunk]
        pos = ci * chunk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # mask p explicitly: with every position masked (zero-length
        # slot), s == m_new == NEG_INF and exp(s - m_new) would be 1
        p = jnp.where(pos < length, jnp.exp(s - m_new), 0.0)  # [G, chunk]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v_buf[slot].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [G, hd]
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[:], 1e-30)  # length==0 rows: zeros, not NaN
    o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def paged_decode_attention_pallas(q: jnp.ndarray, k_pool: jnp.ndarray,
                                  v_pool: jnp.ndarray, tables: jnp.ndarray,
                                  lengths: jnp.ndarray, *,
                                  scale: float | None = None,
                                  interpret: bool = False) -> jnp.ndarray:
    """The Pallas path. q [B, Hq, hd], pools [Hkv, Np, pg, hd]."""
    b, hq, hd = q.shape
    hkv, n_pages, page, _ = k_pool.shape
    _, max_pages = tables.shape
    group = hq // hkv
    scale = scale if scale is not None else hd ** -0.5

    # chunk ~128 rows per softmax fold, in whole pages
    pages_per_chunk = max(1, min(max_pages, -(-128 // page)))
    chunk = pages_per_chunk * page

    q4 = q.reshape(b, hkv, group, hd)
    kernel = functools.partial(
        _paged_decode_kernel, page=page, pages_per_chunk=pages_per_chunk,
        max_pages=max_pages, n_pages=n_pages, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv),
        in_specs=[
            pl.BlockSpec((1, 1, group, hd),
                         lambda i, j, *_: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),      # k pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),      # v pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, 1, group, hd),
                               lambda i, j, *_: (i, j, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, hd), k_pool.dtype),
            pltpu.VMEM((2, chunk, hd), v_pool.dtype),
            pltpu.VMEM((group, hd), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2, pages_per_chunk)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, hd), q.dtype),
        grid_spec=grid_spec,
        # grid cells (slot, kv-head) are independent: declaring them
        # parallel lets Mosaic software-pipeline across cells instead
        # of fencing between iterations
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q4, k_pool, v_pool)
    return out.reshape(b, hq, hd)


# ------------------------------------------------------------ xla fallback

def paged_decode_attention_xla(q: jnp.ndarray, k_pool: jnp.ndarray,
                               v_pool: jnp.ndarray, tables: jnp.ndarray,
                               lengths: jnp.ndarray, *,
                               scale: float | None = None) -> jnp.ndarray:
    """Reference path: gather the slot views, run dense masked decode
    attention. Correct everywhere; materialises [B, Mp*pg, Hkv, hd]."""
    from .attention import decode_attention
    hkv, n_pages, page, hd = k_pool.shape
    b, max_pages = tables.shape
    safe = jnp.minimum(tables, n_pages - 1)
    k_view = k_pool[:, safe].transpose(1, 2, 3, 0, 4).reshape(
        b, max_pages * page, hkv, hd)
    v_view = v_pool[:, safe].transpose(1, 2, 3, 0, 4).reshape(
        b, max_pages * page, hkv, hd)
    return decode_attention(q[:, None], k_view, v_view, lengths,
                            scale=scale)[:, 0]


def paged_decode_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                           v_pool: jnp.ndarray, tables: jnp.ndarray,
                           lengths: jnp.ndarray, *,
                           scale: float | None = None,
                           implementation: str = "auto") -> jnp.ndarray:
    """Dispatch wrapper. implementation: 'pallas'|'interpret'|'xla'|'auto'."""
    if implementation == "pallas" or (
            implementation == "auto" and _is_tpu()):
        return paged_decode_attention_pallas(q, k_pool, v_pool, tables,
                                             lengths, scale=scale)
    if implementation == "interpret":
        return paged_decode_attention_pallas(q, k_pool, v_pool, tables,
                                             lengths, scale=scale,
                                             interpret=True)
    return paged_decode_attention_xla(q, k_pool, v_pool, tables, lengths,
                                      scale=scale)
