"""Ragged paged decode-attention — pages read in place via block table.

The paged KV layout (:mod:`.paged_kv`) stores K/V in a HEAD-MAJOR page
pool ``[Hkv, Np, pg, hd]`` per layer with per-slot block tables. The
generic engine path materialises a dense per-slot view of the WHOLE
pool allocation every K-step pass (``gather_view``), which costs
O(full-cache) extra HBM traffic on top of attention's own reads —
vLLM's layout without vLLM's kernel (round-3 verdict weak #2).

This kernel removes the materialisation: each grid cell (slot b,
kv-head h) walks ONLY the pages covering ``lengths[b]`` rows (ragged —
shorter slots read fewer pages), DMA-ing pages HBM→VMEM double-buffered
and folding them into an online-softmax accumulator. The pool is never
reshaped, copied, or padded to the per-slot maximum.

Head-major matters on real hardware: Mosaic tiles the trailing two
dims of a memref, so slicing a TRAILING head axis to 1 per grid cell
(the r4 ``[Np, pg, Hkv, hd]`` layout) is illegal ("Slice shape along
dimension 2 must be aligned to tiling (8), but is 1" — first real-TPU
compile, r5), while ``pool.at[h, pid]`` slices only untiled leading
dims AND makes each page read a contiguous [pg, hd] block instead of a
strided one.

Layouts (decode, Sq == 1):
- ``q``        [B, Hq, hd]
- ``k_pool``   [Hkv, Np, pg, hd] (one layer's pool; bf16 in serving)
- ``tables``   [B, Mp] int32 — page ids, out-of-range = unallocated
- ``lengths``  [B] int32 — valid rows per slot (AFTER this step's write)
- out          [B, Hq, hd]

``paged_decode_attention`` dispatches: 'pallas' (TPU), 'interpret'
(kernel under the interpreter — CPU tests), 'xla' (gather fallback),
'auto' (pallas on TPU, xla elsewhere).

Quantized pools (``kv_dtype="int8"``) arrive as the two-leaf pytree
``{"q": int8 [Hkv, Np, pg, hd], "s": f32 [Hkv, Np, pg, 1]}`` from
:mod:`.paged_kv`. The kernels DMA each int8 page PLUS its [pg, 1]
scale row (hd+4 bytes per row instead of 2·hd — roughly half the
per-page HBM traffic at hd >= 64) and dequantize in-register
(``codes.astype(f32) * scales``) before the QK/PV matmuls. The
``_xla`` fallbacks and interpret mode dequantize the same way, so the
CPU parity tests compare identical float inputs — the quantization
error cancels and kernel-vs-fallback parity is as tight as bf16's.

The same shape generalises to ragged QUERY blocks
(``paged_chunk_attention``): chunked prefill, prefix-cache suffix
reattachment and speculative verify all feed Sq > 1 new positions per
slot against a per-slot history already in the pool. The grid gains a
q-block axis, each (slot, kv-head, q-block) cell walks only the pages
covering ``history + min((qb+1)·BQ, chunk_len)`` rows, and the causal
mask compares page positions against ``history + q_index``. This is
the prefill-side twin of the decode kernel: with it, no serving hot
path materialises a dense per-slot view of the pool.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# kernel compiles on the installed toolchain either side of the rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

NEG_INF = -1e30

#: Mosaic tiles the trailing two dims of every VMEM memref; the
#: second-to-last ("sublane") dim is tiled in units of 8 rows, so any
#: BlockSpec block or memref slice along it must cover a multiple of 8
#: — BENCH_r05's real-TPU compile died on exactly this ("Slice shape
#: along dimension 2 must be aligned to tiling (8), but is 1") when a
#: grid cell's q block carried fewer than 8 rows (small GQA group).
#: The q/out blocks below are zero-padded up to the tile and sliced
#: back after the call; the pad rows compute finite garbage that never
#: leaves the host wrapper.
SUBLANE = 8


def _pad_group(group: int, block_q: int = 1) -> int:
    """Smallest padded GQA group size such that a q block of
    ``block_q * group_padded`` rows is sublane-aligned (multiple of
    8). ``block_q >= 8`` (always a power of two) needs no padding."""
    step = SUBLANE // math.gcd(block_q, SUBLANE)
    return -(-group // step) * step


#: int8 memrefs tile the sublane dim in units of 32 rows (vs 8 for
#: f32/bf16) — see the dtype tiling table in the Pallas TPU docs — so
#: a quantized pool's page size must be a multiple of 32 for the
#: per-page slices of the int8 double buffer to stay tile-aligned.
SUBLANE_INT8 = 32


def _check_page_alignment(page: int, interpret: bool,
                          quantized: bool = False) -> None:
    """The per-page DMA lands each page at row offset ``j * page`` of
    the VMEM double buffer — a slice along the sublane dim, so the
    page size must be tile-aligned on real hardware (interpret mode on
    CPU has no tiling). The engine's default page_size=64 is fine for
    both dtypes; this turns a cryptic Mosaic error into an actionable
    one."""
    sublane = SUBLANE_INT8 if quantized else SUBLANE
    if not interpret and page % sublane:
        raise ValueError(
            f"page size {page} is not a multiple of {sublane}: the TPU "
            f"kernel DMAs whole pages into sublane-tiled VMEM "
            f"({'int8 tiles 32 rows' if quantized else '8-row tiles'}) "
            f"— use a page_size multiple of {sublane} (or the "
            f"'xla'/'view' path)")


def _split_pool(pool):
    """(codes, scales-or-None) for either pool representation."""
    if isinstance(pool, dict):
        return pool["q"], pool["s"]
    return pool, None


def _is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ------------------------------------------------------------------ kernel

def _paged_decode_kernel(tables_ref, lengths_ref, q_ref, k_hbm, v_hbm,
                         *rest, page: int, pages_per_chunk: int,
                         max_pages: int, n_pages: int, scale: float,
                         quantized: bool = False):
    if quantized:
        (ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf,
         acc_ref, m_ref, l_ref, sems) = rest
    else:
        o_ref, k_buf, v_buf, acc_ref, m_ref, l_ref, sems = rest
    b = pl.program_id(0)
    h = pl.program_id(1)
    chunk = pages_per_chunk * page
    length = lengths_ref[b]
    n_chunks = jnp.maximum(pl.cdiv(length, chunk), 1)

    def page_dmas(ci, slot):
        # one DMA per page: pages are scattered in the pool, so a
        # chunk is pages_per_chunk independent copies — each a
        # CONTIGUOUS [page, hd] block in the head-major pool. A
        # quantized pool adds the [page, 1] f32 scale row per page.
        dmas = []
        for j in range(pages_per_chunk):
            # tail chunks index past the table: clamp — their rows are
            # masked off by `length` below, they just must not fault
            page_idx = jnp.minimum(ci * pages_per_chunk + j,
                                   max_pages - 1)
            pid = jnp.minimum(tables_ref[b, page_idx], n_pages - 1)
            dst = pl.ds(j * page, page)
            dmas.append(pltpu.make_async_copy(
                k_hbm.at[h, pid], k_buf.at[slot, dst, :],
                sems.at[slot, 0, j]))
            dmas.append(pltpu.make_async_copy(
                v_hbm.at[h, pid], v_buf.at[slot, dst, :],
                sems.at[slot, 1, j]))
            if quantized:
                dmas.append(pltpu.make_async_copy(
                    ks_hbm.at[h, pid], ks_buf.at[slot, dst, :],
                    sems.at[slot, 2, j]))
                dmas.append(pltpu.make_async_copy(
                    vs_hbm.at[h, pid], vs_buf.at[slot, dst, :],
                    sems.at[slot, 3, j]))
        return dmas

    def start_chunk(ci, slot):
        for dma in page_dmas(ci, slot):
            dma.start()

    def wait_chunk(ci, slot):
        for dma in page_dmas(ci, slot):
            dma.wait()

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    start_chunk(0, 0)
    qf = q_ref[0, 0].astype(jnp.float32) * scale        # [G, hd]

    def body(ci, _):
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < n_chunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        k = k_buf[slot].astype(jnp.float32)             # [chunk, hd]
        if quantized:
            k = k * ks_buf[slot]        # in-register dequant, [chunk, 1]
        s = jax.lax.dot_general(
            qf, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [G, chunk]
        pos = ci * chunk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # mask p explicitly: with every position masked (zero-length
        # slot), s == m_new == NEG_INF and exp(s - m_new) would be 1
        p = jnp.where(pos < length, jnp.exp(s - m_new), 0.0)  # [G, chunk]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_buf[slot].astype(jnp.float32)             # [chunk, hd]
        if quantized:
            v = v * vs_buf[slot]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [G, hd]
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[:], 1e-30)  # length==0 rows: zeros, not NaN
    o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def paged_decode_attention_pallas(q: jnp.ndarray, k_pool,
                                  v_pool, tables: jnp.ndarray,
                                  lengths: jnp.ndarray, *,
                                  scale: float | None = None,
                                  interpret: bool = False) -> jnp.ndarray:
    """The Pallas path. q [B, Hq, hd], pools [Hkv, Np, pg, hd] (plain)
    or the ``{"q", "s"}`` quantized pytree."""
    k_codes, k_scales = _split_pool(k_pool)
    v_codes, v_scales = _split_pool(v_pool)
    quantized = k_scales is not None
    b, hq, hd = q.shape
    hkv, n_pages, page, _ = k_codes.shape
    _, max_pages = tables.shape
    group = hq // hkv
    scale = scale if scale is not None else hd ** -0.5
    _check_page_alignment(page, interpret, quantized)

    # chunk ~128 rows per softmax fold, in whole pages
    pages_per_chunk = max(1, min(max_pages, -(-128 // page)))
    chunk = pages_per_chunk * page

    # sublane alignment: each grid cell's q/out block is [group, hd]
    # rows — pad the GQA group axis up to the 8-row tile (MHA group=1
    # was BENCH_r05's Mosaic failure) and slice the pad back off below
    group_p = _pad_group(group)
    q4 = q.reshape(b, hkv, group, hd)
    if group_p != group:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, group_p - group), (0, 0)))
    kernel = functools.partial(
        _paged_decode_kernel, page=page, pages_per_chunk=pages_per_chunk,
        max_pages=max_pages, n_pages=n_pages, scale=scale,
        quantized=quantized)
    # scale rows ride as two extra HBM operands + two f32 double
    # buffers; the semaphore array gains a pair of rows for them
    scale_specs = [pl.BlockSpec(memory_space=pl.ANY)] * 2 \
        if quantized else []
    scale_bufs = [pltpu.VMEM((2, chunk, 1), jnp.float32)] * 2 \
        if quantized else []
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv),
        in_specs=[
            pl.BlockSpec((1, 1, group_p, hd),
                         lambda i, j, *_: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),      # k pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),      # v pool stays in HBM
            *scale_specs,
        ],
        out_specs=pl.BlockSpec((1, 1, group_p, hd),
                               lambda i, j, *_: (i, j, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, hd), k_codes.dtype),
            pltpu.VMEM((2, chunk, hd), v_codes.dtype),
            *scale_bufs,
            pltpu.VMEM((group_p, hd), jnp.float32),
            pltpu.VMEM((group_p, 1), jnp.float32),
            pltpu.VMEM((group_p, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 4 if quantized else 2,
                                     pages_per_chunk)),
        ],
    )
    args = [tables.astype(jnp.int32), lengths.astype(jnp.int32),
            q4, k_codes, v_codes]
    if quantized:
        args += [k_scales, v_scales]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group_p, hd), q.dtype),
        grid_spec=grid_spec,
        # grid cells (slot, kv-head) are independent: declaring them
        # parallel lets Mosaic software-pipeline across cells instead
        # of fencing between iterations
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(*args)
    if group_p != group:
        out = out[:, :, :group]
    return out.reshape(b, hq, hd)


# ------------------------------------------------------------ xla fallback

def _slot_view(pool, tables: jnp.ndarray) -> jnp.ndarray:
    """Gather one layer's pool into the dense slot view
    [B, Mp*pg, Hkv, hd]. Quantized pools dequantize here with exactly
    the kernels' ``codes.astype(f32) * scales`` contraction, so the
    fallback sees identical float values."""
    codes, scales = _split_pool(pool)
    hkv, n_pages, page, _ = codes.shape
    b, max_pages = tables.shape
    safe = jnp.minimum(tables, n_pages - 1)

    def gather(x):
        return x[:, safe].transpose(1, 2, 3, 0, 4).reshape(
            b, max_pages * page, hkv, x.shape[-1])

    view = gather(codes)
    if scales is not None:
        view = view.astype(jnp.float32) * gather(scales)
    return view


def paged_decode_attention_xla(q: jnp.ndarray, k_pool,
                               v_pool, tables: jnp.ndarray,
                               lengths: jnp.ndarray, *,
                               scale: float | None = None) -> jnp.ndarray:
    """Reference path: gather the slot views, run dense masked decode
    attention. Correct everywhere; materialises [B, Mp*pg, Hkv, hd]."""
    from .attention import decode_attention
    k_view = _slot_view(k_pool, tables)
    v_view = _slot_view(v_pool, tables)
    out = decode_attention(q[:, None], k_view, v_view, lengths,
                           scale=scale)[:, 0]
    # zero-length slots: every position is masked, so the dense softmax
    # degrades to a uniform average over garbage rows — the kernel's
    # denom clamp returns exact zeros there. Match it, so the fallback
    # and the kernel agree on EVERY row, not just live ones.
    return jnp.where(lengths[:, None, None] > 0, out,
                     jnp.zeros_like(out))


# ----------------------------------------------------- chunk (Sq > 1)

def _paged_chunk_kernel(tables_ref, history_ref, chunk_ref, q_ref,
                        k_hbm, v_hbm, *rest, page: int,
                        pages_per_chunk: int, max_pages: int,
                        n_pages: int, scale: float, block_q: int,
                        group: int, quantized: bool = False):
    if quantized:
        (ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf,
         acc_ref, m_ref, l_ref, sems) = rest
    else:
        o_ref, k_buf, v_buf, acc_ref, m_ref, l_ref, sems = rest
    b = pl.program_id(0)
    h = pl.program_id(1)
    qb = pl.program_id(2)
    chunk = pages_per_chunk * page
    hist = history_ref[b]
    clen = chunk_ref[b]
    # rows this q-block may attend to: the full history plus the
    # in-chunk causal prefix ending at the block's last row, bounded
    # by what the chunk actually wrote. clen == 0 rows are padding —
    # they read whatever the walk covers and are discarded upstream.
    kv_limit = hist + jnp.minimum((qb + 1) * block_q, clen)
    n_chunks = jnp.maximum(pl.cdiv(kv_limit, chunk), 1)

    def page_dmas(ci, slot):
        dmas = []
        for j in range(pages_per_chunk):
            page_idx = jnp.minimum(ci * pages_per_chunk + j,
                                   max_pages - 1)
            pid = jnp.minimum(tables_ref[b, page_idx], n_pages - 1)
            dst = pl.ds(j * page, page)
            dmas.append(pltpu.make_async_copy(
                k_hbm.at[h, pid], k_buf.at[slot, dst, :],
                sems.at[slot, 0, j]))
            dmas.append(pltpu.make_async_copy(
                v_hbm.at[h, pid], v_buf.at[slot, dst, :],
                sems.at[slot, 1, j]))
            if quantized:
                dmas.append(pltpu.make_async_copy(
                    ks_hbm.at[h, pid], ks_buf.at[slot, dst, :],
                    sems.at[slot, 2, j]))
                dmas.append(pltpu.make_async_copy(
                    vs_hbm.at[h, pid], vs_buf.at[slot, dst, :],
                    sems.at[slot, 3, j]))
        return dmas

    def start_chunk(ci, slot):
        for dma in page_dmas(ci, slot):
            dma.start()

    def wait_chunk(ci, slot):
        for dma in page_dmas(ci, slot):
            dma.wait()

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    start_chunk(0, 0)
    rows = block_q * group
    # q arrives pre-flattened to [BQ*G, hd] rows: row r is query index
    # r // group, at absolute position history + qb*BQ + r//group
    q_pos = hist + qb * block_q + \
        jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // group
    qf = q_ref[0, 0].astype(jnp.float32) * scale        # [BQ*G, hd]

    def body(ci, _):
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < n_chunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        k = k_buf[slot].astype(jnp.float32)             # [chunk, hd]
        if quantized:
            k = k * ks_buf[slot]        # in-register dequant, [chunk, 1]
        s = jax.lax.dot_general(
            qf, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [BQ*G, chunk]
        pos = ci * chunk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        # causal against history + in-chunk prefix: position p is
        # visible to query q_idx iff p <= history + q_idx (the chunk's
        # own row q_idx was written before attention, like decode).
        # The pos < hist + clen bound is a no-op for valid rows
        # (q_idx < clen implies q_pos < hist + clen) but turns
        # zero-length slots — hist == clen == 0, every position masked
        # — into exact zeros via the denom clamp instead of finite
        # garbage, matching the decode kernel's contract.
        visible = (pos <= q_pos) & (pos < hist + clen)
        s = jnp.where(visible, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # mask p explicitly: a fully-masked row has s == m_new ==
        # NEG_INF and exp(s - m_new) would be 1
        p = jnp.where(visible, jnp.exp(s - m_new), 0.0)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_buf[slot].astype(jnp.float32)             # [chunk, hd]
        if quantized:
            v = v * vs_buf[slot]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [BQ*G, hd]
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[:], 1e-30)  # all-masked rows: zeros
    o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _pick_block_q(sq: int) -> int:
    """Largest power-of-two divisor of Sq, capped at 128 (one MXU pass
    of q rows); non-power-of-two chunk widths fall back to smaller
    divisors so the grid tiles Sq exactly."""
    for cand in (128, 64, 32, 16, 8, 4, 2, 1):
        if sq % cand == 0:
            return min(cand, sq)
    return 1


def paged_chunk_attention_pallas(q: jnp.ndarray, k_pool,
                                 v_pool, tables: jnp.ndarray,
                                 history_lens: jnp.ndarray,
                                 chunk_lens: jnp.ndarray, *,
                                 scale: float | None = None,
                                 block_q: int | None = None,
                                 interpret: bool = False) -> jnp.ndarray:
    """Ragged chunk attention. q [B, Sq, Hq, hd] holds Sq new positions
    per slot, already written into the pool at rows
    ``[history_lens, history_lens + chunk_lens)``; pools
    [Hkv, Np, pg, hd] (plain) or the ``{"q", "s"}`` quantized pytree.
    Query row i of slot b attends causally to pool
    rows <= history_lens[b] + i, bounded by the slot's written total
    ``history + chunk``. Rows past ``chunk_lens[b]`` are padding the
    caller discards; zero-length slots (history == chunk == 0) return
    exact zeros, like the decode kernel."""
    k_codes, k_scales = _split_pool(k_pool)
    v_codes, v_scales = _split_pool(v_pool)
    quantized = k_scales is not None
    b, sq, hq, hd = q.shape
    hkv, n_pages, page, _ = k_codes.shape
    _, max_pages = tables.shape
    group = hq // hkv
    scale = scale if scale is not None else hd ** -0.5
    if block_q is None:
        block_q = _pick_block_q(sq)
    if sq % block_q != 0:
        raise ValueError(f"block_q {block_q} must divide Sq {sq}")
    _check_page_alignment(page, interpret, quantized)

    pages_per_chunk = max(1, min(max_pages, -(-128 // page)))
    chunk = pages_per_chunk * page

    # [B, Hkv, Sq*G, hd]: q rows flattened OUTSIDE the kernel so each
    # grid cell reads a plain 2D [BQ*G, hd] block — the q-block axis
    # slices the (tiled) second-to-last dim in BQ*G-row steps. Those
    # steps must be sublane-aligned (multiples of 8): narrow blocks
    # (short chunks x small GQA group — e.g. a spec-verify window with
    # block_q=1) pad the group axis up to the tile and slice the pad
    # back off the output below.
    group_p = _pad_group(group, block_q)
    q5 = q.reshape(b, sq, hkv, group, hd)
    if group_p != group:
        q5 = jnp.pad(q5, ((0, 0), (0, 0), (0, 0),
                          (0, group_p - group), (0, 0)))
    q4 = q5.transpose(0, 2, 1, 3, 4).reshape(b, hkv, sq * group_p, hd)
    kernel = functools.partial(
        _paged_chunk_kernel, page=page, pages_per_chunk=pages_per_chunk,
        max_pages=max_pages, n_pages=n_pages, scale=scale,
        block_q=block_q, group=group_p, quantized=quantized)
    rows = block_q * group_p
    scale_specs = [pl.BlockSpec(memory_space=pl.ANY)] * 2 \
        if quantized else []
    scale_bufs = [pltpu.VMEM((2, chunk, 1), jnp.float32)] * 2 \
        if quantized else []
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, hkv, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, rows, hd),
                         lambda i, j, k, *_: (i, j, k, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),      # k pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),      # v pool stays in HBM
            *scale_specs,
        ],
        out_specs=pl.BlockSpec((1, 1, rows, hd),
                               lambda i, j, k, *_: (i, j, k, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, hd), k_codes.dtype),
            pltpu.VMEM((2, chunk, hd), v_codes.dtype),
            *scale_bufs,
            pltpu.VMEM((rows, hd), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 4 if quantized else 2,
                                     pages_per_chunk)),
        ],
    )
    args = [tables.astype(jnp.int32), history_lens.astype(jnp.int32),
            chunk_lens.astype(jnp.int32), q4, k_codes, v_codes]
    if quantized:
        args += [k_scales, v_scales]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, sq * group_p, hd),
                                       q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
        interpret=interpret,
    )(*args)
    return out.reshape(b, hkv, sq, group_p, hd) \
        .transpose(0, 2, 1, 3, 4)[:, :, :, :group] \
        .reshape(b, sq, hq, hd)


def paged_chunk_attention_xla(q: jnp.ndarray, k_pool,
                              v_pool, tables: jnp.ndarray,
                              history_lens: jnp.ndarray,
                              chunk_lens: jnp.ndarray, *,
                              scale: float | None = None) -> jnp.ndarray:
    """Reference path: gather the slot views, run dense causal
    attention offset by the history. Materialises [B, Mp*pg, Hkv, hd]
    per call — the traffic the kernel exists to avoid."""
    from .attention import xla_attention
    k_view = _slot_view(k_pool, tables)
    v_view = _slot_view(v_pool, tables)
    out = xla_attention(q, k_view, v_view, causal=True,
                        q_offset=history_lens,
                        kv_lengths=history_lens + chunk_lens,
                        scale=scale)
    # zero-length slots (hist == clen == 0): every position is masked
    # and the dense softmax degrades to a uniform average over garbage
    # — the kernel returns exact zeros there. Match it so kernel and
    # fallback agree on every row of every slot.
    total = history_lens + chunk_lens
    return jnp.where(total[:, None, None, None] > 0, out,
                     jnp.zeros_like(out))


# ---------------------------------------------- tree verify (Sq > 1)
#
# Speculative tree verify: the Sq rows of a verify pass are NODES of a
# draft tree (node 0 = the committed root token, nodes packed
# topologically so every parent index < child index), not a linear
# chunk. Node i must attend the full history plus its ANCESTOR nodes
# only — two sibling branches must not see each other, or the verify
# logits would differ from the sequential decode they stand in for.
# The per-node ancestor set rides as a packed int32 bitmask
# (bit j set iff node j is an ancestor of node i, or j == i), which
# caps the tree at 32 nodes — far above any sane draft budget.

def _paged_tree_kernel(tables_ref, history_ref, chunk_ref, tree_ref,
                       q_ref, k_hbm, v_hbm, *rest, page: int,
                       pages_per_chunk: int, max_pages: int,
                       n_pages: int, scale: float, block_q: int,
                       group: int, quantized: bool = False):
    if quantized:
        (ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf,
         acc_ref, m_ref, l_ref, sems) = rest
    else:
        o_ref, k_buf, v_buf, acc_ref, m_ref, l_ref, sems = rest
    b = pl.program_id(0)
    h = pl.program_id(1)
    qb = pl.program_id(2)
    chunk = pages_per_chunk * page
    hist = history_ref[b]
    clen = chunk_ref[b]
    # topological packing (parent < child) means a node's ancestors
    # all sit at lower rows, so the chunk kernel's ragged page walk
    # bound is still exact: block qb never needs rows past
    # hist + min((qb+1)*BQ, clen)
    kv_limit = hist + jnp.minimum((qb + 1) * block_q, clen)
    n_chunks = jnp.maximum(pl.cdiv(kv_limit, chunk), 1)

    def page_dmas(ci, slot):
        dmas = []
        for j in range(pages_per_chunk):
            page_idx = jnp.minimum(ci * pages_per_chunk + j,
                                   max_pages - 1)
            pid = jnp.minimum(tables_ref[b, page_idx], n_pages - 1)
            dst = pl.ds(j * page, page)
            dmas.append(pltpu.make_async_copy(
                k_hbm.at[h, pid], k_buf.at[slot, dst, :],
                sems.at[slot, 0, j]))
            dmas.append(pltpu.make_async_copy(
                v_hbm.at[h, pid], v_buf.at[slot, dst, :],
                sems.at[slot, 1, j]))
            if quantized:
                dmas.append(pltpu.make_async_copy(
                    ks_hbm.at[h, pid], ks_buf.at[slot, dst, :],
                    sems.at[slot, 2, j]))
                dmas.append(pltpu.make_async_copy(
                    vs_hbm.at[h, pid], vs_buf.at[slot, dst, :],
                    sems.at[slot, 3, j]))
        return dmas

    def start_chunk(ci, slot):
        for dma in page_dmas(ci, slot):
            dma.start()

    def wait_chunk(ci, slot):
        for dma in page_dmas(ci, slot):
            dma.wait()

    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    start_chunk(0, 0)
    rows = block_q * group
    # broadcast each row's packed ancestor mask out of SMEM: a gather
    # by traced per-row index is not Mosaic-expressible, but block_q
    # is static and small, so an unrolled select ladder over the
    # block's nodes builds the [rows, 1] mask vector from scalar loads
    ridx = jax.lax.broadcasted_iota(
        jnp.int32, (rows, 1), 0) // group       # local node 0..BQ-1
    mask_row = jnp.zeros((rows, 1), jnp.int32)
    for t in range(block_q):
        mask_row = jnp.where(ridx == t,
                             tree_ref[b, qb * block_q + t], mask_row)
    qf = q_ref[0, 0].astype(jnp.float32) * scale        # [BQ*G, hd]

    def body(ci, _):
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < n_chunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        k = k_buf[slot].astype(jnp.float32)             # [chunk, hd]
        if quantized:
            k = k * ks_buf[slot]
        s = jax.lax.dot_general(
            qf, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [BQ*G, chunk]
        pos = ci * chunk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        # history rows (pos < hist) are visible to every node; tree
        # rows (rel = pos - hist in [0, clen)) are visible iff the
        # node's ancestor bit for them is set
        rel = pos - hist
        bit = jax.lax.shift_right_logical(
            mask_row, jnp.clip(rel, 0, 31)) & 1
        visible = (rel < 0) | ((rel < clen) & (bit == 1))
        s = jnp.where(visible, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(visible, jnp.exp(s - m_new), 0.0)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_buf[slot].astype(jnp.float32)             # [chunk, hd]
        if quantized:
            v = v * vs_buf[slot]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [BQ*G, hd]
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[:], 1e-30)  # all-masked rows: zeros
    o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def paged_tree_attention_pallas(q: jnp.ndarray, k_pool,
                                v_pool, tables: jnp.ndarray,
                                history_lens: jnp.ndarray,
                                chunk_lens: jnp.ndarray,
                                tree_masks: jnp.ndarray, *,
                                scale: float | None = None,
                                block_q: int | None = None,
                                interpret: bool = False) -> jnp.ndarray:
    """Tree-verify attention. q [B, Sq, Hq, hd] holds the Sq draft-tree
    nodes per slot, already written into the pool at rows
    ``[history_lens, history_lens + chunk_lens)`` in topological order
    (parent row < child row); ``tree_masks`` [B, Sq] int32 packs each
    node's ancestor-or-self set as bits over the in-chunk node index.
    Node i of slot b attends pool rows < history_lens[b] plus in-chunk
    rows j with bit j of tree_masks[b, i] set. Nodes past
    ``chunk_lens[b]`` are padding; a fully-masked row returns zeros."""
    k_codes, k_scales = _split_pool(k_pool)
    v_codes, v_scales = _split_pool(v_pool)
    quantized = k_scales is not None
    b, sq, hq, hd = q.shape
    if sq > 32:
        raise ValueError(f"tree width {sq} exceeds the 32-node packed "
                         f"ancestor bitmask")
    hkv, n_pages, page, _ = k_codes.shape
    _, max_pages = tables.shape
    group = hq // hkv
    scale = scale if scale is not None else hd ** -0.5
    if block_q is None:
        block_q = _pick_block_q(sq)
    if sq % block_q != 0:
        raise ValueError(f"block_q {block_q} must divide Sq {sq}")
    _check_page_alignment(page, interpret, quantized)

    pages_per_chunk = max(1, min(max_pages, -(-128 // page)))
    chunk = pages_per_chunk * page

    group_p = _pad_group(group, block_q)
    q5 = q.reshape(b, sq, hkv, group, hd)
    if group_p != group:
        q5 = jnp.pad(q5, ((0, 0), (0, 0), (0, 0),
                          (0, group_p - group), (0, 0)))
    q4 = q5.transpose(0, 2, 1, 3, 4).reshape(b, hkv, sq * group_p, hd)
    kernel = functools.partial(
        _paged_tree_kernel, page=page, pages_per_chunk=pages_per_chunk,
        max_pages=max_pages, n_pages=n_pages, scale=scale,
        block_q=block_q, group=group_p, quantized=quantized)
    rows = block_q * group_p
    scale_specs = [pl.BlockSpec(memory_space=pl.ANY)] * 2 \
        if quantized else []
    scale_bufs = [pltpu.VMEM((2, chunk, 1), jnp.float32)] * 2 \
        if quantized else []
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, hkv, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, rows, hd),
                         lambda i, j, k, *_: (i, j, k, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),      # k pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),      # v pool stays in HBM
            *scale_specs,
        ],
        out_specs=pl.BlockSpec((1, 1, rows, hd),
                               lambda i, j, k, *_: (i, j, k, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, hd), k_codes.dtype),
            pltpu.VMEM((2, chunk, hd), v_codes.dtype),
            *scale_bufs,
            pltpu.VMEM((rows, hd), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 4 if quantized else 2,
                                     pages_per_chunk)),
        ],
    )
    args = [tables.astype(jnp.int32), history_lens.astype(jnp.int32),
            chunk_lens.astype(jnp.int32), tree_masks.astype(jnp.int32),
            q4, k_codes, v_codes]
    if quantized:
        args += [k_scales, v_scales]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, sq * group_p, hd),
                                       q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
        interpret=interpret,
    )(*args)
    return out.reshape(b, hkv, sq, group_p, hd) \
        .transpose(0, 2, 1, 3, 4)[:, :, :, :group] \
        .reshape(b, sq, hq, hd)


def paged_tree_attention_xla(q: jnp.ndarray, k_pool,
                             v_pool, tables: jnp.ndarray,
                             history_lens: jnp.ndarray,
                             chunk_lens: jnp.ndarray,
                             tree_masks: jnp.ndarray, *,
                             scale: float | None = None) -> jnp.ndarray:
    """Reference path: gather the slot views, run dense tree-masked
    attention. Materialises [B, Mp*pg, Hkv, hd] per call."""
    from .attention import tree_attention
    k_view = _slot_view(k_pool, tables)
    v_view = _slot_view(v_pool, tables)
    out = tree_attention(q, k_view, v_view,
                         history_lens=history_lens,
                         chunk_lens=chunk_lens,
                         tree_masks=tree_masks, scale=scale)
    # zero-length slots (hist == clen == 0): every position is masked
    # and the dense softmax degrades to a uniform average over garbage
    # — the kernel's denom clamp returns exact zeros there. Match it
    # so kernel and fallback agree on every row of every slot (the
    # decode and chunk fallbacks above already do; this parity is what
    # lets output digests compare across implementations bit-for-bit).
    total = history_lens + chunk_lens
    return jnp.where(total[:, None, None, None] > 0, out,
                     jnp.zeros_like(out))


def paged_tree_attention(q: jnp.ndarray, k_pool,
                         v_pool, tables: jnp.ndarray,
                         history_lens: jnp.ndarray,
                         chunk_lens: jnp.ndarray,
                         tree_masks: jnp.ndarray, *,
                         scale: float | None = None,
                         implementation: str = "auto") -> jnp.ndarray:
    """Dispatch wrapper. implementation: 'pallas'|'interpret'|'xla'|'auto'."""
    if implementation == "pallas" or (
            implementation == "auto" and _is_tpu()):
        return paged_tree_attention_pallas(q, k_pool, v_pool, tables,
                                           history_lens, chunk_lens,
                                           tree_masks, scale=scale)
    if implementation == "interpret":
        return paged_tree_attention_pallas(q, k_pool, v_pool, tables,
                                           history_lens, chunk_lens,
                                           tree_masks, scale=scale,
                                           interpret=True)
    return paged_tree_attention_xla(q, k_pool, v_pool, tables,
                                    history_lens, chunk_lens, tree_masks,
                                    scale=scale)


def paged_chunk_attention(q: jnp.ndarray, k_pool,
                          v_pool, tables: jnp.ndarray,
                          history_lens: jnp.ndarray,
                          chunk_lens: jnp.ndarray, *,
                          scale: float | None = None,
                          implementation: str = "auto") -> jnp.ndarray:
    """Dispatch wrapper. implementation: 'pallas'|'interpret'|'xla'|'auto'."""
    if implementation == "pallas" or (
            implementation == "auto" and _is_tpu()):
        return paged_chunk_attention_pallas(q, k_pool, v_pool, tables,
                                            history_lens, chunk_lens,
                                            scale=scale)
    if implementation == "interpret":
        return paged_chunk_attention_pallas(q, k_pool, v_pool, tables,
                                            history_lens, chunk_lens,
                                            scale=scale, interpret=True)
    return paged_chunk_attention_xla(q, k_pool, v_pool, tables,
                                     history_lens, chunk_lens, scale=scale)


def paged_decode_attention(q: jnp.ndarray, k_pool,
                           v_pool, tables: jnp.ndarray,
                           lengths: jnp.ndarray, *,
                           scale: float | None = None,
                           implementation: str = "auto") -> jnp.ndarray:
    """Dispatch wrapper. implementation: 'pallas'|'interpret'|'xla'|'auto'."""
    if implementation == "pallas" or (
            implementation == "auto" and _is_tpu()):
        return paged_decode_attention_pallas(q, k_pool, v_pool, tables,
                                             lengths, scale=scale)
    if implementation == "interpret":
        return paged_decode_attention_pallas(q, k_pool, v_pool, tables,
                                             lengths, scale=scale,
                                             interpret=True)
    return paged_decode_attention_xla(q, k_pool, v_pool, tables, lengths,
                                      scale=scale)
