"""Attention: XLA reference implementation + Pallas flash dispatch.

Layouts follow the serving stack: ``q`` is ``[B, Sq, Hq, D]``, ``k``/``v``
are ``[B, Skv, Hkv, D]`` with grouped-query attention when ``Hq > Hkv``.
Logits and softmax run in float32; inputs/outputs stay bf16.

``attention`` is the prefill path (causal, optional per-sequence kv
lengths for padded batches); ``decode_attention`` is the single-token
decode path against a cache. ``implementation='auto'`` uses the Pallas
flash kernel on TPU and the XLA reference elsewhere (CPU tests run the
kernel in interpret mode).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _repeat_kv(x: jnp.ndarray, group: int) -> jnp.ndarray:
    """[B, S, Hkv, D] -> [B, S, Hkv*group, D] for GQA."""
    if group == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, group, d)).reshape(
        b, s, h * group, d)


def xla_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                  causal: bool = True,
                  kv_lengths: jnp.ndarray | None = None,
                  q_offset: jnp.ndarray | int = 0,
                  scale: float | None = None) -> jnp.ndarray:
    """Reference attention. q [B,Sq,Hq,D]; k,v [B,Skv,Hkv,D].

    ``q_offset``: absolute position of q row 0 (scalar or [B]) so chunked
    prefill keeps causal alignment against a longer kv history.
    """
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    group = hq // hkv
    k = _repeat_kv(k, group)
    v = _repeat_kv(v, group)
    scale = scale if scale is not None else d ** -0.5

    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale

    q_pos = jnp.arange(sq)[None, :]  # [1, Sq]
    if isinstance(q_offset, int):
        q_pos = q_pos + q_offset  # [1, Sq]
    else:
        q_pos = q_pos + q_offset[:, None]  # [B, Sq]
    kv_pos = jnp.arange(skv)  # [Skv]

    mask = jnp.ones((q_pos.shape[0], sq, skv), dtype=bool)
    if causal:
        mask = kv_pos[None, None, :] <= q_pos[:, :, None]
    if kv_lengths is not None:
        mask = mask & (kv_pos[None, None, :] < kv_lengths[:, None, None])
    logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)

    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights, v.astype(jnp.float32))
    return out.astype(q.dtype)


def tree_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   history_lens: jnp.ndarray,
                   chunk_lens: jnp.ndarray,
                   tree_masks: jnp.ndarray,
                   scale: float | None = None) -> jnp.ndarray:
    """Draft-tree verify attention. q [B,Sq,Hq,D] holds Sq tree nodes
    per slot (topological order, node 0 = root); k/v [B,Skv,Hkv,D] hold
    the history followed by the tree nodes at rows
    ``[history_lens, history_lens + chunk_lens)``. Node i attends every
    history row plus exactly the in-tree rows whose bit is set in
    ``tree_masks[b, i]`` (packed ancestor-or-self bits over the
    in-chunk node index — Sq <= 32). Fully-masked rows return zeros,
    matching the paged kernel's denom-clamp contract."""
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    if sq > 32:
        raise ValueError(f"tree width {sq} exceeds the 32-node packed "
                         f"ancestor bitmask")
    group = hq // hkv
    k = _repeat_kv(k, group)
    v = _repeat_kv(v, group)
    scale = scale if scale is not None else d ** -0.5

    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale

    kv_pos = jnp.arange(skv)[None, None, :]               # [1, 1, Skv]
    rel = kv_pos - history_lens[:, None, None]            # [B, 1, Skv]
    bit = (tree_masks[:, :, None].astype(jnp.int32)
           >> jnp.clip(rel, 0, 31)) & 1                   # [B, Sq, Skv]
    visible = (rel < 0) | ((rel < chunk_lens[:, None, None]) & (bit == 1))
    logits = jnp.where(visible[:, None, :, :], logits, NEG_INF)

    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights, v.astype(jnp.float32))
    # a fully-masked node row (padding with no history) softmaxes to a
    # uniform average of garbage — zero it like the kernel does
    any_visible = visible.any(axis=-1)                    # [B, Sq]
    out = jnp.where(any_visible[:, :, None, None], out,
                    jnp.zeros_like(out))
    return out.astype(q.dtype)


def decode_attention(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                     kv_lengths: jnp.ndarray,
                     scale: float | None = None) -> jnp.ndarray:
    """Single-step decode: q [B,1,Hq,D] against cache [B,Smax,Hkv,D].

    Every cache row at position < kv_lengths[b] participates. This is
    the XLA path; the engine batches many sequences so the matmuls stay
    MXU-shaped even at Sq=1.
    """
    b, sq, hq, d = q.shape
    _, smax, hkv, _ = k_cache.shape
    group = hq // hkv
    scale = scale if scale is not None else d ** -0.5

    # einsums run in the cache dtype (bf16 in serving) with f32
    # accumulation — no materialised f32 copy of the [B,Smax,Hkv,D]
    # cache per layer; only the [.., Smax] logits/weights are f32.
    qr = q.astype(k_cache.dtype).reshape(b, sq, hkv, group, d)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qr, k_cache,
                        preferred_element_type=jnp.float32) * scale
    mask = jnp.arange(smax)[None, :] < kv_lengths[:, None]  # [B, Smax]
    logits = jnp.where(mask[:, None, None, None, :], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", weights.astype(v_cache.dtype),
                     v_cache, preferred_element_type=jnp.float32)
    return out.reshape(b, sq, hq, d).astype(q.dtype)


def attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
              causal: bool = True,
              kv_lengths: jnp.ndarray | None = None,
              q_offset: jnp.ndarray | int = 0,
              scale: float | None = None,
              implementation: str = "auto",
              block_q: int = 128, block_k: int = 128) -> jnp.ndarray:
    """Prefill attention with implementation dispatch.

    implementation: 'xla' | 'pallas' | 'interpret' | 'auto'.
    The pallas path requires causal attention and int(q_offset)==0 (the
    serving prefill shape); anything else falls back to XLA.
    """
    use_pallas = False
    interpret = False
    if implementation == "pallas":
        use_pallas = True
    elif implementation == "interpret":
        use_pallas, interpret = True, True
    elif implementation == "auto":
        use_pallas = _is_tpu() and causal and isinstance(q_offset, int) \
            and q_offset == 0 and q.shape[1] > 1
    if use_pallas:
        from .flash_attention import flash_attention
        return flash_attention(q, k, v, kv_lengths=kv_lengths, scale=scale,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret)
    return xla_attention(q, k, v, causal=causal, kv_lengths=kv_lengths,
                         q_offset=q_offset, scale=scale)
