"""Audio frontend: log-mel spectrogram, fully in JAX.

The Whisper-family feature extractor (16 kHz PCM -> [frames, n_mels]
log-mel), expressed as jittable array ops so it fuses into the encoder
program and runs on the TPU instead of a host-side DSP library: framing
is a gather, the STFT is ``jnp.fft.rfft`` over Hann-windowed frames,
and the mel projection is one matmul (MXU) with a filterbank built
once in numpy at trace time.
"""

from __future__ import annotations

import functools

import numpy as np

import jax.numpy as jnp

SAMPLE_RATE = 16000
N_FFT = 400
HOP_LENGTH = 160
CHUNK_SECONDS = 30


def _hz_to_mel(hz):
    return 2595.0 * np.log10(1.0 + np.asarray(hz) / 700.0)


def _mel_to_hz(mel):
    return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)


@functools.lru_cache(maxsize=8)
def mel_filterbank(n_mels: int = 80, n_fft: int = N_FFT,
                   sample_rate: int = SAMPLE_RATE) -> np.ndarray:
    """[n_fft//2+1, n_mels] triangular filters (HTK mel scale),
    area-normalised per filter."""
    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sample_rate / 2, n_bins)
    mel_points = np.linspace(_hz_to_mel(0.0), _hz_to_mel(sample_rate / 2),
                             n_mels + 2)
    hz_points = _mel_to_hz(mel_points)
    bank = np.zeros((n_bins, n_mels), dtype=np.float32)
    for m in range(n_mels):
        left, center, right = hz_points[m], hz_points[m + 1], hz_points[m + 2]
        up = (fft_freqs - left) / max(center - left, 1e-10)
        down = (right - fft_freqs) / max(right - center, 1e-10)
        tri = np.maximum(0.0, np.minimum(up, down))
        norm = (right - left) / 2
        bank[:, m] = tri / max(norm, 1e-10)
    return bank


def log_mel_spectrogram(audio: jnp.ndarray, *, n_mels: int = 80,
                        n_fft: int = N_FFT, hop: int = HOP_LENGTH,
                        sample_rate: int = SAMPLE_RATE,
                        pad_to_frames: int | None = None) -> jnp.ndarray:
    """PCM [T] or [B, T] float in [-1, 1] -> log-mel [B, frames, n_mels].

    Matches the Whisper recipe: Hann window, power spectrum, mel
    projection, ``log10`` clamped to 8 orders of dynamic range, scaled
    to roughly [-1, 1]. ``pad_to_frames`` right-pads/truncates to a
    fixed frame count so the encoder sees a static shape.
    """
    if audio.ndim == 1:
        audio = audio[None, :]
    b, t = audio.shape
    audio = audio.astype(jnp.float32)

    # reflect-pad half a window each side (librosa/whisper centering)
    pad = n_fft // 2
    audio = jnp.pad(audio, ((0, 0), (pad, pad)), mode="reflect")
    n_frames = 1 + (audio.shape[1] - n_fft) // hop

    idx = (jnp.arange(n_frames)[:, None] * hop
           + jnp.arange(n_fft)[None, :])          # [frames, n_fft]
    frames = audio[:, idx]                          # [B, frames, n_fft]
    window = jnp.hanning(n_fft + 1)[:-1].astype(jnp.float32)
    # explicit lift to frames' rank (rank_promotion='raise' under test)
    spectrum = jnp.fft.rfft(frames * window[None, None, :], n=n_fft,
                            axis=-1)
    power = jnp.abs(spectrum) ** 2                  # [B, frames, n_fft//2+1]

    bank = jnp.asarray(mel_filterbank(n_mels, n_fft, sample_rate))
    mel = power @ bank                              # [B, frames, n_mels]

    log_mel = jnp.log10(jnp.maximum(mel, 1e-10))
    log_mel = jnp.maximum(log_mel, log_mel.max(axis=(-2, -1),
                                               keepdims=True) - 8.0)
    log_mel = (log_mel + 4.0) / 4.0

    if pad_to_frames is not None:
        have = log_mel.shape[1]
        if have < pad_to_frames:
            log_mel = jnp.pad(
                log_mel, ((0, 0), (0, pad_to_frames - have), (0, 0)))
        else:
            log_mel = log_mel[:, :pad_to_frames, :]
    return log_mel
