"""Pallas flash attention for TPU (causal prefill).

Online-softmax tiling: grid ``(B, Hq, Sq/BQ)``; each step streams K/V
blocks for one (batch, head) through VMEM with float32 running
max/sum/accumulator. GQA maps query head ``h`` to kv head ``h // group``
in the BlockSpec index map, so kv heads are never materialized
``group``-fold. Per-sequence lengths arrive via scalar prefetch so
padded batches mask correctly.

VMEM budget: one q block [BQ, D] + full K,V rows [Skv, D] per grid step
— bf16 Skv=4096, D=128 is ~2 MB, well inside ~16 MB VMEM. Longer
sequences should go through ring attention (gofr_tpu/parallel) or the
XLA path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# kernels compile on the installed toolchain either side of the rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

NEG_INF = -1e30


def _flash_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *,
                  scale: float, block_k: int, seq_kv: int, block_q: int):
    b = pl.program_id(0)
    qi = pl.program_id(2)

    q = q_ref[0, 0].astype(jnp.float32) * scale        # [BQ, D]
    kv_len = len_ref[b]

    bq, d = q.shape
    acc = jnp.zeros((bq, d), jnp.float32)
    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)

    row = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)

    num_blocks = pl.cdiv(seq_kv, block_k)

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T                                  # [BQ, BK]
        col = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        mask = (col <= row) & (col < kv_len)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + p @ v_blk
        return acc_new, m_new, l_new

    # causal: kv blocks strictly after this q block contribute nothing
    last = jnp.minimum(num_blocks,
                       pl.cdiv((qi + 1) * block_q, block_k))
    acc, m, l = jax.lax.fori_loop(0, last, body, (acc, m, l))

    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    kv_lengths: jnp.ndarray | None = None,
                    scale: float | None = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False) -> jnp.ndarray:
    """Causal flash attention. q [B,Sq,Hq,D]; k,v [B,Skv,Hkv,D]."""
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    group = hq // hkv
    scale = scale if scale is not None else d ** -0.5

    block_q = min(block_q, max(sq, 8))
    block_k = min(block_k, max(skv, 128))

    # layout: [B, H, S, D] for MXU-friendly tiles
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    pad_q = (-sq) % block_q
    pad_k = (-skv) % block_k
    if pad_q:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    sq_p, skv_p = sq + pad_q, skv + pad_k

    if kv_lengths is None:
        kv_lengths = jnp.full((b,), skv, jnp.int32)
    kv_lengths = kv_lengths.astype(jnp.int32)

    grid = (b, hq, sq_p // block_q)

    kernel = functools.partial(_flash_kernel, scale=scale, block_k=block_k,
                               seq_kv=skv_p, block_q=block_q)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda bi, hi, qi, lens: (bi, hi, qi, 0)),
                pl.BlockSpec((1, 1, skv_p, d),
                             lambda bi, hi, qi, lens: (bi, hi // group, 0, 0)),
                pl.BlockSpec((1, 1, skv_p, d),
                             lambda bi, hi, qi, lens: (bi, hi // group, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, d),
                                   lambda bi, hi, qi, lens: (bi, hi, qi, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq_p, d), q.dtype),
        # (batch, head, q-block) cells carry no cross-iteration state —
        # the online-softmax accumulator lives within one cell's k loop
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
        interpret=interpret,
    )(kv_lengths, qt, kt, vt)

    out = jnp.swapaxes(out, 1, 2)  # [B, Sq_p, Hq, D]
    if pad_q:
        out = out[:, :sq]
    return out
