"""Paged KV cache primitives — block-table indirection over a page pool.

The serving engine's paged layout (vLLM-style, re-designed for XLA's
static-shape world): K/V live in a HEAD-MAJOR pool
``[L, Hkv, n_pages, page, hd]`` and each slot owns an ordered list of
page ids (its *block table*, shape ``[max_pages]``). Capacity is
decoupled from ``max_batch x max_seq``: slots allocate pages as they
grow and free them on retire, so many long-tailed requests overcommit
a pool that a contiguous per-slot layout could never fit.

Head-major (kv-head axis OUTSIDE the page grid) is the TPU-native
choice: the ragged paged-attention kernel's per-(head, page) DMA is a
contiguous ``[page, hd]`` block — Mosaic requires slices of the tiled
trailing dims to be tile-aligned, so a trailing head axis (the r4
layout) cannot be sliced per-grid-cell at all, and head-major also
makes every page read stride-free. The Mosaic error this fixes:
"Slice shape along dimension 2 must be aligned to tiling (8), but is
1" (scripts/tpu_results/02_pallas_smoke.py.json, r5).

Everything here is a pure jittable function on static shapes:

- :func:`gather_view` materialises a slot-contiguous ``[L, B, S, ...]``
  view once per K-step decode pass (NOT per token) — the engine then
  runs the model family's ordinary dense decode step on the view, so
  paged mode needs zero model changes.
- :func:`scatter_prefill` / :func:`scatter_decode` write prompt slabs /
  freshly decoded rows back through the table. Unallocated positions
  map to the out-of-range page id (``n_pages``), which XLA's scatter
  drops — padding rows and dummy slots cost nothing and corrupt
  nothing.

Free-list bookkeeping is host-side (``serving/engine.py``): the device
never sees an allocator, only tables.
"""

from __future__ import annotations

import jax.numpy as jnp


def gather_view(pool: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    """Pool [L, H, Np, pg, d] + tables [B, Mp] -> view [L, B, Mp*pg, H, d].

    Out-of-range table entries (unallocated = Np) clamp to the last
    page on gather; those rows are masked by the caller's kv_lengths.
    """
    l, h, np_, pg, d = pool.shape
    b, mp = tables.shape
    view = pool[:, :, tables]                   # [L, H, B, Mp, pg, d]
    view = view.transpose(0, 2, 3, 4, 1, 5)     # [L, B, Mp, pg, H, d]
    return view.reshape(l, b, mp * pg, h, d)


def scatter_prefill(pool: jnp.ndarray, tables: jnp.ndarray,
                    k_slab: jnp.ndarray) -> jnp.ndarray:
    """Write a prompt K (or V) slab [L, P, S, H, d] into the pool via
    per-row tables [P, Mp]. Positions whose table entry is the OOB page
    id are dropped (padding beyond each row's allocation, dummy rows).
    """
    pg = pool.shape[3]
    s = k_slab.shape[2]
    pos = jnp.arange(s)
    pids = jnp.take(tables, pos // pg, axis=1)          # [P, S]
    offs = jnp.broadcast_to(pos % pg, pids.shape)       # [P, S]
    slab = k_slab.transpose(0, 3, 1, 2, 4)              # [L, H, P, S, d]
    return pool.at[:, :, pids, offs].set(slab, mode="drop")


def scatter_chunk(pool: jnp.ndarray, tables: jnp.ndarray,
                  slab: jnp.ndarray, offsets: jnp.ndarray,
                  chunk_lens: jnp.ndarray) -> jnp.ndarray:
    """Write a chunk slab [L, P, S, H, d] whose row b covers logical
    positions ``[offsets[b], offsets[b] + chunk_lens[b])`` into the
    pool — touching only the pages the chunk spans. ``scatter_prefill``
    writes every slab position of every row (pad rows past a prompt's
    real length included, dropped only where the table has no page);
    here rows past ``chunk_lens`` and positions past the table map to
    the OOB page id and drop, so a 5-token suffix in a 512-wide bucket
    writes one page, not the slot's whole allocation.
    """
    pg = pool.shape[3]
    n_pages = pool.shape[2]
    mp = tables.shape[1]
    s = slab.shape[2]
    pos = offsets[:, None] + jnp.arange(s)[None, :]             # [P, S]
    valid = jnp.arange(s)[None, :] < chunk_lens[:, None]        # [P, S]
    pids = jnp.take_along_axis(
        tables, jnp.clip(pos // pg, 0, mp - 1), axis=1)         # [P, S]
    pids = jnp.where(valid & (pos < mp * pg), pids, n_pages)
    offs = pos % pg
    rows = slab.transpose(0, 3, 1, 2, 4)                # [L, H, P, S, d]
    return pool.at[:, :, pids, offs].set(rows, mode="drop")


def scatter_decode(pool: jnp.ndarray, tables: jnp.ndarray,
                   view: jnp.ndarray, lengths: jnp.ndarray,
                   k_steps: int) -> jnp.ndarray:
    """Copy the ``k_steps`` rows a decode pass appended to ``view``
    (at logical positions lengths .. lengths+K-1 per slot) back into
    the pool. view [L, B, S, H, d], tables [B, Mp], lengths [B].
    """
    pg = pool.shape[3]
    n_pages = pool.shape[2]
    s = view.shape[2]
    positions = lengths[:, None] + jnp.arange(k_steps)[None, :]   # [B, K]
    clamped = jnp.minimum(positions, s - 1)
    new_rows = jnp.take_along_axis(
        view, clamped[None, :, :, None, None], axis=2)  # [L, B, K, H, d]
    pids = jnp.take_along_axis(tables, clamped // pg, axis=1)     # [B, K]
    # positions past the logical view (a slot at the cache ceiling
    # taking a partial pass) must drop, not overwrite the last row
    pids = jnp.where(positions < s, pids, n_pages)
    offs = clamped % pg
    rows = new_rows.transpose(0, 3, 1, 2, 4)            # [L, H, B, K, d]
    return pool.at[:, :, pids, offs].set(rows, mode="drop")


def pool_from_cache_shape(k_cache: jnp.ndarray) -> jnp.ndarray:
    """Re-lay a dense [L, Np, pg, H, d] allocation (what
    ``make_cache(n_pages, page)`` returns) as the head-major pool
    [L, H, Np, pg, d]. Zero-cost on zeros; used by the engine so model
    glue only needs one cache constructor."""
    return k_cache.transpose(0, 3, 1, 2, 4)
