"""Paged KV cache primitives — block-table indirection over a page pool.

The serving engine's paged layout (vLLM-style, re-designed for XLA's
static-shape world): K/V live in a HEAD-MAJOR pool
``[L, Hkv, n_pages, page, hd]`` and each slot owns an ordered list of
page ids (its *block table*, shape ``[max_pages]``). Capacity is
decoupled from ``max_batch x max_seq``: slots allocate pages as they
grow and free them on retire, so many long-tailed requests overcommit
a pool that a contiguous per-slot layout could never fit.

Head-major (kv-head axis OUTSIDE the page grid) is the TPU-native
choice: the ragged paged-attention kernel's per-(head, page) DMA is a
contiguous ``[page, hd]`` block — Mosaic requires slices of the tiled
trailing dims to be tile-aligned, so a trailing head axis (the r4
layout) cannot be sliced per-grid-cell at all, and head-major also
makes every page read stride-free. The Mosaic error this fixes:
"Slice shape along dimension 2 must be aligned to tiling (8), but is
1" (scripts/tpu_results/02_pallas_smoke.py.json, r5).

Everything here is a pure jittable function on static shapes:

- :func:`gather_view` materialises a slot-contiguous ``[L, B, S, ...]``
  view once per K-step decode pass (NOT per token) — the engine then
  runs the model family's ordinary dense decode step on the view, so
  paged mode needs zero model changes.
- :func:`scatter_prefill` / :func:`scatter_decode` write prompt slabs /
  freshly decoded rows back through the table. Unallocated positions
  map to the out-of-range page id (``n_pages``), which XLA's scatter
  drops — padding rows and dummy slots cost nothing and corrupt
  nothing.

Free-list bookkeeping is host-side (``serving/engine.py``): the device
never sees an allocator, only tables.

Quantized pools
---------------
``kv_dtype="int8"`` swaps the plain ``[L, Hkv, Np, pg, hd]`` array for
a two-leaf pytree ``{"q": int8 [L, Hkv, Np, pg, hd],
"s": f32 [L, Hkv, Np, pg, 1]}`` — narrow codes plus one f32 scale per
written ROW (same ``amax / 127`` contract as
:func:`gofr_tpu.ops.quant.quantize_int8` with ``axis=-1``). Per-row
(not per-page-scalar) granularity is load-bearing: decode appends one
row to a partially filled page, and a page-wide amax recomputation
would silently re-quantize — and degrade — rows written earlier. The
trailing singleton keeps the scale slice a 2-D ``[page, 1]`` block so
the ragged kernels can DMA it exactly like the page itself.

Every scatter quantizes ON WRITE inside the same jitted graph (the
engine's hot closures never dequantize host-side or ``.astype`` the
pool — ``gofrlint``'s kv-quant-boundary rule pins this), and
:func:`gather_view` dequantizes for the view fallback. bf16 pools stay
plain arrays so the default path compiles the exact seed graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: a pool is either a plain array or this two-leaf quantized pytree
QUANT_KEYS = ("q", "s")


def is_quantized_pool(pool) -> bool:
    """True for the ``{"q": int8, "s": f32}`` quantized pool pytree."""
    return isinstance(pool, dict)


def quantize_rows(rows: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Rows [..., d] -> (int8 codes [..., d], f32 scales [..., 1]).

    Same contract as ``quantize_int8(w, axis=-1)``: symmetric,
    ``scale = max(amax, 1e-8) / 127``, codes clipped to ±127. Zero rows
    quantize to all-zero codes (scale floor), so fresh pool pages
    dequantize to exact zeros.
    """
    rf = rows.astype(jnp.float32)
    amax = jnp.max(jnp.abs(rf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(rf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_rows(q: jnp.ndarray, s: jnp.ndarray,
                    dtype=jnp.float32) -> jnp.ndarray:
    """Codes [..., d] * scales [..., 1] -> values [..., d] in ``dtype``."""
    return (q.astype(jnp.float32) * s).astype(dtype)


def quantize_pool(pool: jnp.ndarray) -> dict:
    """Re-lay a plain head-major pool [L, H, Np, pg, d] as the
    quantized pytree (per-row scales). Used at allocation time and by
    tests; steady-state writes go through the scatters."""
    q, s = quantize_rows(pool)
    return {"q": q, "s": s}


def pool_shape(pool) -> tuple:
    """[L, H, Np, pg, d] logical shape for either pool representation."""
    return pool["q"].shape if is_quantized_pool(pool) else pool.shape


def pool_row_bytes(pool) -> int:
    """HBM bytes per KV ROW (one token, all layers/heads, K or V side
    only) — includes the per-row scale overhead for quantized pools."""
    if is_quantized_pool(pool):
        l, h, _, _, d = pool["q"].shape
        return l * h * (d * pool["q"].dtype.itemsize
                        + pool["s"].dtype.itemsize)
    l, h, _, _, d = pool.shape
    return l * h * d * pool.dtype.itemsize


def pool_layer(pool, li):
    """Layer ``li``'s [H, Np, pg, d] slice (pytree-aware) — what the
    ragged attention dispatchers take as ``k_pool`` / ``v_pool``."""
    if is_quantized_pool(pool):
        return {k: jax.lax.dynamic_index_in_dim(pool[k], li, 0,
                                                keepdims=False)
                for k in QUANT_KEYS}
    return jax.lax.dynamic_index_in_dim(pool, li, 0, keepdims=False)


def pool_write(pool, li, pids, offs, rows):
    """Write ``rows`` into layer ``li`` at (page, offset) coordinates —
    the single-layer scatter the model families use inside their layer
    scan. ``pids``/``offs`` are the advanced-index arrays ([B] decode,
    [B, S] chunk); ``rows`` matches the advanced-index result shape
    ([B, H, d] / [B, S, H, d]). Quantizes on write for quantized pools;
    plain pools absorb the dtype cast here so callers never touch the
    pool dtype."""
    if is_quantized_pool(pool):
        q, s = quantize_rows(rows)
        return {"q": pool["q"].at[li, :, pids, offs].set(q, mode="drop"),
                "s": pool["s"].at[li, :, pids, offs].set(s, mode="drop")}
    return pool.at[li, :, pids, offs].set(rows.astype(pool.dtype),
                                          mode="drop")


def _pool_set(pool, pids, offs, rows):
    """All-layer scatter: rows [L, H, P, S, d] at pids/offs [P, S]."""
    if is_quantized_pool(pool):
        q, s = quantize_rows(rows)
        return {"q": pool["q"].at[:, :, pids, offs].set(q, mode="drop"),
                "s": pool["s"].at[:, :, pids, offs].set(s, mode="drop")}
    return pool.at[:, :, pids, offs].set(rows.astype(pool.dtype),
                                         mode="drop")


def gather_view(pool, tables: jnp.ndarray,
                dtype=None) -> jnp.ndarray:
    """Pool [L, H, Np, pg, d] + tables [B, Mp] -> view [L, B, Mp*pg, H, d].

    Out-of-range table entries (unallocated = Np) clamp to the last
    page on gather; those rows are masked by the caller's kv_lengths.
    Quantized pools dequantize here (``dtype`` picks the view dtype,
    default bf16); for plain pools ``dtype`` is ignored — the view is
    the pool dtype, exactly as before.
    """
    if is_quantized_pool(pool):
        qv = _gather_raw(pool["q"], tables)     # [L, B, S, H, d] int8
        sv = _gather_raw(pool["s"], tables)     # [L, B, S, H, 1] f32
        return dequantize_rows(
            qv, sv, jnp.bfloat16 if dtype is None else dtype)
    return _gather_raw(pool, tables)


def _gather_raw(pool: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    l, h, np_, pg, d = pool.shape
    b, mp = tables.shape
    view = pool[:, :, tables]                   # [L, H, B, Mp, pg, d]
    view = view.transpose(0, 2, 3, 4, 1, 5)     # [L, B, Mp, pg, H, d]
    return view.reshape(l, b, mp * pg, h, d)


def scatter_prefill(pool, tables: jnp.ndarray,
                    k_slab: jnp.ndarray):
    """Write a prompt K (or V) slab [L, P, S, H, d] into the pool via
    per-row tables [P, Mp]. Positions whose table entry is the OOB page
    id are dropped (padding beyond each row's allocation, dummy rows).
    """
    pg = pool_shape(pool)[3]
    s = k_slab.shape[2]
    pos = jnp.arange(s)
    pids = jnp.take(tables, pos // pg, axis=1)          # [P, S]
    offs = jnp.broadcast_to(pos % pg, pids.shape)       # [P, S]
    slab = k_slab.transpose(0, 3, 1, 2, 4)              # [L, H, P, S, d]
    return _pool_set(pool, pids, offs, slab)


def scatter_chunk(pool, tables: jnp.ndarray,
                  slab: jnp.ndarray, offsets: jnp.ndarray,
                  chunk_lens: jnp.ndarray):
    """Write a chunk slab [L, P, S, H, d] whose row b covers logical
    positions ``[offsets[b], offsets[b] + chunk_lens[b])`` into the
    pool — touching only the pages the chunk spans. ``scatter_prefill``
    writes every slab position of every row (pad rows past a prompt's
    real length included, dropped only where the table has no page);
    here rows past ``chunk_lens`` and positions past the table map to
    the OOB page id and drop, so a 5-token suffix in a 512-wide bucket
    writes one page, not the slot's whole allocation.
    """
    n_pages, pg = pool_shape(pool)[2:4]
    mp = tables.shape[1]
    s = slab.shape[2]
    pos = offsets[:, None] + jnp.arange(s)[None, :]             # [P, S]
    valid = jnp.arange(s)[None, :] < chunk_lens[:, None]        # [P, S]
    pids = jnp.take_along_axis(
        tables, jnp.clip(pos // pg, 0, mp - 1), axis=1)         # [P, S]
    pids = jnp.where(valid & (pos < mp * pg), pids, n_pages)
    offs = pos % pg
    rows = slab.transpose(0, 3, 1, 2, 4)                # [L, H, P, S, d]
    return _pool_set(pool, pids, offs, rows)


def scatter_decode(pool, tables: jnp.ndarray,
                   view: jnp.ndarray, lengths: jnp.ndarray,
                   k_steps: int):
    """Copy the ``k_steps`` rows a decode pass appended to ``view``
    (at logical positions lengths .. lengths+K-1 per slot) back into
    the pool. view [L, B, S, H, d], tables [B, Mp], lengths [B].
    """
    n_pages, pg = pool_shape(pool)[2:4]
    s = view.shape[2]
    positions = lengths[:, None] + jnp.arange(k_steps)[None, :]   # [B, K]
    clamped = jnp.minimum(positions, s - 1)
    new_rows = jnp.take_along_axis(
        view, clamped[None, :, :, None, None], axis=2)  # [L, B, K, H, d]
    pids = jnp.take_along_axis(tables, clamped // pg, axis=1)     # [B, K]
    # positions past the logical view (a slot at the cache ceiling
    # taking a partial pass) must drop, not overwrite the last row
    pids = jnp.where(positions < s, pids, n_pages)
    offs = clamped % pg
    rows = new_rows.transpose(0, 3, 1, 2, 4)            # [L, H, B, K, d]
    return _pool_set(pool, pids, offs, rows)


def pool_move_rows(pool, tables: jnp.ndarray,
                   src_pos: jnp.ndarray, dst_pos: jnp.ndarray):
    """Move KV rows between logical positions of each slot:
    row ``src_pos[b, k]`` -> ``dst_pos[b, k]`` through slot b's table.
    Used by speculative tree verify to compact the accepted
    root-to-leaf path out of the node-indexed scratch rows.

    Moves the RAW pool representation — int8 codes plus their f32
    scale rows for quantized pools — so the copy is exact by
    construction: no dequantize/requantize round trip. All gathers
    complete before any scatter (one advanced-index gather, one
    scatter), so overlapping src/dst sets cannot order-corrupt.
    Entries with ``dst_pos`` outside the slot's table (the caller's
    "no move" sentinel) drop; ``src_pos`` for those entries may be
    anything in-range-clamped.
    """
    n_pages, pg = pool_shape(pool)[2:4]
    mp = tables.shape[1]

    def coords(pos, clamp):
        pids = jnp.take_along_axis(
            tables, jnp.clip(pos // pg, 0, mp - 1), axis=1)
        pids = jnp.where((pos >= 0) & (pos < mp * pg), pids, n_pages)
        if clamp:
            pids = jnp.minimum(pids, n_pages - 1)
        return pids, pos % pg

    s_pids, s_offs = coords(src_pos, clamp=True)
    d_pids, d_offs = coords(dst_pos, clamp=False)

    def move(arr):
        rows = arr[:, :, s_pids, s_offs]            # [L, H, B, K, d]
        return arr.at[:, :, d_pids, d_offs].set(rows, mode="drop")

    if is_quantized_pool(pool):
        return {k: move(pool[k]) for k in QUANT_KEYS}
    return move(pool)


def pool_from_cache_shape(k_cache: jnp.ndarray) -> jnp.ndarray:
    """Re-lay a dense [L, Np, pg, H, d] allocation (what
    ``make_cache(n_pages, page)`` returns) as the head-major pool
    [L, H, Np, pg, d]. Zero-cost on zeros; used by the engine so model
    glue only needs one cache constructor."""
    return k_cache.transpose(0, 3, 1, 2, 4)
