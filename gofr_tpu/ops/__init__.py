from .norms import layer_norm, rms_norm
from .rope import apply_rope, rope_frequencies
from .attention import attention, decode_attention
from .sampling import sample_tokens
from .moe import moe_layer, top_k_routing

__all__ = [
    "layer_norm", "rms_norm", "apply_rope", "rope_frequencies",
    "attention", "decode_attention", "sample_tokens",
    "moe_layer", "top_k_routing",
]
