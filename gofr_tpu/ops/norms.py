"""Normalization ops.

TPU notes: norms are bandwidth-bound VPU work that XLA fuses into the
surrounding matmuls; computing the statistics in float32 and casting
back keeps bf16 stability without blocking fusion.
"""

from __future__ import annotations

import jax.lax
import jax.numpy as jnp


def _row(v: jnp.ndarray, ndim: int) -> jnp.ndarray:
    """Explicitly lift a rank-1 per-channel vector to ``ndim`` for the
    trailing axis — the tests run with jax_numpy_rank_promotion='raise',
    so implicit (B, S, D) op (D,) broadcasting is an error."""
    return v.reshape((1,) * (ndim - 1) + (-1,))


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm (Llama-family): x * w / rms(x), stats in f32."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * _row(weight.astype(jnp.float32), normed.ndim)).astype(dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-6) -> jnp.ndarray:
    """LayerNorm (BERT/Whisper-family), stats in f32."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) * (var + eps) ** -0.5
    out = (normed * _row(weight.astype(jnp.float32), normed.ndim)
           + _row(bias.astype(jnp.float32), normed.ndim))
    return out.astype(dtype)
