"""Weight-only int8 / int4 quantization — the HBM-bandwidth lever.

Decode is memory-bound: every generated token streams every parameter
out of HBM once per batch. Storing weights as int8 (or int4 — XLA
packs two per byte on TPU) with per-channel scales halves (quarters)
that traffic, which on a memory-bound roofline is up to a 2x (4x)
decode-throughput ceiling — while matmuls still run in the activation
dtype on the MXU (weight-only: no activation quantization; int4's
per-channel scheme costs more accuracy on real checkpoints than
int8's — group-wise scales are the standard mitigation and can layer
onto this representation).

Representation: a quantized matrix is the dict ``{"q": int8/int4
array, "s": f32 scales}`` — a plain pytree node, so optimizers/
checkpoints/jit see ordinary leaves. Scales are per-output-channel
(max-abs over the contraction axis divided by the int range: 127 for
int8, 8 for int4 — int4 uses the full asymmetric two's-complement
range [-8, 7]), the standard symmetric scheme; ``x @ q * s``
applies the scale AFTER the matmul, so XLA reads the narrow integers
from HBM and fuses the upcast into the matmul's operand load. Scales
store as f32 (bandwidth noise — one scalar per output channel): the
backbone dequant rounds them to the activation dtype anyway, but the
f32 LM-head path keeps the full precision where logits are computed.

``quantize_llama_int8`` / ``quantize_llama_int4`` rewrite a Llama
parameter tree in place-shape: the seven per-layer matrices and the
embedding (per-row scales — it serves both the input gather and,
tied, the LM head). Norm gains stay in full precision (tiny, and
sensitive).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp


def quantize_int8(w: jnp.ndarray, *, axis: int = 0) -> dict:
    """Symmetric per-channel int8: ``axis`` is the REDUCED axis (the
    contraction axis of the later matmul), so scales are per output
    channel. w [.., in, out] with axis=-2 -> s [.., 1, out]."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                 -127, 127).astype(jnp.int8)
    # f32 scales (see module docstring for the dtype rationale)
    return {"q": q, "s": scale}


def quantize_int4(w: jnp.ndarray, *, axis: int = 0) -> dict:
    """Per-channel int4 over the FULL [-8, 7] two's-complement range:
    a quarter of the bf16 HBM stream — XLA packs two int4 values per
    byte on TPU. Same post-matmul scale contract as int8, so every
    qmatmul/sharding/serving path works unchanged. Per-channel (not
    group-wise) keeps the scale OUTSIDE the contraction, which is what
    lets the weight stream stay int4 end-to-end instead of
    dequantising into a materialised bf16 copy.

    scale = amax / 8 uses the -8 code point (an extra level of
    precision over the old symmetric [-7, 7] scheme — a ~14% smaller
    step); the one asymmetry is the exact-amax guard: a weight equal
    to +amax would round to +8, which int4 cannot represent, so the
    clip pins it to +7 (error bounded by one step for exactly that
    value, half a step everywhere else)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 8.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                 -8, 7).astype(jnp.int4)
    return {"q": q, "s": scale}


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def _lift(s: jnp.ndarray, ndim: int) -> jnp.ndarray:
    """Explicitly pad a scale's leading rank to ``ndim`` — the test
    harness runs jax_numpy_rank_promotion='raise', so the post-matmul
    ``y * s`` broadcast must not rely on implicit promotion."""
    return s.reshape((1,) * (ndim - s.ndim) + s.shape)


def qmatmul(x: jnp.ndarray, w: Any, *,
            out_dtype: Any = None) -> jnp.ndarray:
    """x @ w for plain or quantized ``w`` (scale applied post-matmul)."""
    if not is_quantized(w):
        return jnp.matmul(x.astype(w.dtype), w,
                          preferred_element_type=out_dtype or x.dtype)
    y = jnp.matmul(x, w["q"].astype(x.dtype),
                   preferred_element_type=out_dtype or x.dtype)
    return y * _lift(w["s"].astype(y.dtype), y.ndim)


def qgather(w: Any, idx: jnp.ndarray, dtype: Any) -> jnp.ndarray:
    """Embedding-table row gather for plain or quantized tables.
    Quantized tables carry per-row scales [V, 1]."""
    if not is_quantized(w):
        return w[idx]
    return (w["q"][idx].astype(dtype) * w["s"][idx].astype(dtype))


def qmatmul_t(x: jnp.ndarray, w: Any, *, out_dtype: Any = None) -> jnp.ndarray:
    """x @ w.T for plain or quantized ``w`` — the tied-embedding LM
    head path: the table's per-row scales [V, 1] become the head's
    per-output-channel scales."""
    if not is_quantized(w):
        return jnp.matmul(x.astype(w.dtype), w.T,
                          preferred_element_type=out_dtype or x.dtype)
    y = jnp.matmul(x, w["q"].T.astype(x.dtype),
                   preferred_element_type=out_dtype or x.dtype)
    return y * _lift(w["s"].reshape(-1).astype(y.dtype), y.ndim)


#: the 4-bit dtypes XLA packs two-per-byte on TPU
_INT4_DTYPES = tuple(jnp.dtype(d) for d in (jnp.int4, jnp.uint4))


def quantized_bytes(tree: Any) -> int:
    """Bytes a pytree occupies as stored on TPU (int8 leaves count
    1 byte, int4/uint4 half a byte, plus scales). Works on any tree:
    quantized weight dicts AND the paged KV pool's ``{"q", "s"}``
    pytree (``ops/paged_kv.py``) — the per-row scale leaves are just
    more leaves, so the engine's ``kv_bytes`` accounting is one call
    over ``(k_cache, v_cache)``. The 0.5 B/param figure is the
    INTENDED packed size — XLA packs two 4-bit values per byte on
    TPU — not a measured allocation; a backend that keeps int4
    unpacked (CPU does) actually spends a full byte per value."""
    import jax
    total = 0.0
    for leaf in jax.tree.leaves(tree):
        if jnp.dtype(leaf.dtype) in _INT4_DTYPES:
            total += leaf.size * 0.5
        else:
            total += leaf.size * leaf.dtype.itemsize
    return int(total)


def _quantize_llama(params: dict, qfn) -> dict:
    out: dict = {"final_norm": params["final_norm"]}
    layers = params["layers"]
    qlayers: dict = {}
    for name, w in layers.items():
        if name.endswith("_norm"):
            qlayers[name] = w
        else:  # [L, in, out]: reduce axis 1 -> scales [L, 1, out]
            qlayers[name] = qfn(w, axis=1)
    out["layers"] = qlayers
    # embed [V, D]: per-row scales serve the gather AND the tied head
    out["embed"] = qfn(params["embed"], axis=1)
    if "lm_head" in params:  # [D, V]: reduce axis 0
        out["lm_head"] = qfn(params["lm_head"], axis=0)
    return out


def quantize_llama_int8(params: dict) -> dict:
    """Quantize a Llama tree: per-layer matrices ([L, in, out] — reduce
    the ``in`` axis) + embedding (per-row) + untied lm_head. Norm gains
    pass through untouched."""
    return _quantize_llama(params, quantize_int8)


def quantize_llama_int4(params: dict) -> dict:
    """int4 variant of :func:`quantize_llama_int8` — a quarter of the
    bf16 weight stream. Per-channel symmetric; expect a larger
    accuracy cost than int8 on real checkpoints (group-wise scales are
    the standard mitigation and can layer onto this representation)."""
    return _quantize_llama(params, quantize_int4)
