"""Token sampling: greedy / temperature / top-k / top-p, jit-friendly.

All shapes static; the sampling mode is baked at trace time (the engine
buckets requests by sampling config). Gumbel-max sampling avoids an
explicit categorical draw.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(logits: jnp.ndarray, key: jax.Array, *,
                  temperature: float = 1.0,
                  top_k: int = 0,
                  top_p: float = 1.0) -> jnp.ndarray:
    """Sample next tokens from logits [B, V] -> [B] int32.

    temperature == 0.0 -> greedy. top_k/top_p filter before the draw.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    logits = logits.astype(jnp.float32) / max(temperature, 1e-6)

    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]  # [B, 1]
        logits = jnp.where(logits < kth, NEG_INF, logits)

    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative mass exceeds top_p (always >=1 kept)
        keep_sorted = jnp.roll(cum, 1, axis=-1) < top_p
        keep_sorted = keep_sorted.at[..., 0].set(True)
        # threshold logit: smallest kept logit
        kept_logits = jnp.where(keep_sorted, sorted_logits, jnp.inf)
        threshold = jnp.min(kept_logits, axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, NEG_INF, logits)

    gumbel = -jnp.log(-jnp.log(
        jax.random.uniform(key, logits.shape, minval=1e-20, maxval=1.0) + 1e-20))
    return jnp.argmax(logits + gumbel, axis=-1).astype(jnp.int32)
