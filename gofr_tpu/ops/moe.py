"""Mixture-of-experts ops: top-k routing + gated expert MLP.

The dense formulation here computes every expert for every token and
combines with routing weights — correct, static-shaped, and the
building block the EP-sharded path reuses: with experts sharded over a
mesh axis, each device computes only its expert slice of the same
einsums and the combine is a ``psum`` (see gofr_tpu/parallel).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def top_k_routing(gate_logits: jnp.ndarray, k: int,
                  renormalize: bool = True) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Route tokens: [T, E] logits -> (weights [T, k], indices [T, k])."""
    values, indices = jax.lax.top_k(gate_logits, k)
    if renormalize:
        weights = jax.nn.softmax(values.astype(jnp.float32), axis=-1)
    else:
        weights = jax.nn.softmax(
            gate_logits.astype(jnp.float32), axis=-1)
        weights = jnp.take_along_axis(weights, indices, axis=-1)
    return weights, indices


def moe_layer(x: jnp.ndarray, gate_w: jnp.ndarray, w1: jnp.ndarray,
              w3: jnp.ndarray, w2: jnp.ndarray, *, num_selected: int = 2
              ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Mixtral-style sparse MLP.

    x [T, Dm]; gate_w [Dm, E]; w1,w3 [E, Dm, F]; w2 [E, F, Dm].
    Returns (output [T, Dm], router_logits [T, E] for aux loss).
    """
    dtype = x.dtype
    gate_logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)  # [T, E]
    weights, indices = top_k_routing(gate_logits, num_selected)

    # combine[t, e] = routing weight of expert e for token t (0 if unrouted)
    num_experts = gate_w.shape[-1]
    onehot = jax.nn.one_hot(indices, num_experts, dtype=jnp.float32)  # [T,k,E]
    combine = jnp.einsum("tk,tke->te", weights, onehot)  # [T, E]

    xf = x.astype(jnp.float32)
    up = jnp.einsum("td,edf->tef", xf, w1.astype(jnp.float32))
    gate = jnp.einsum("td,edf->tef", xf, w3.astype(jnp.float32))
    hidden = jax.nn.silu(up) * gate
    expert_out = jnp.einsum("tef,efd->ted", hidden, w2.astype(jnp.float32))
    out = jnp.einsum("te,ted->td", combine, expert_out)
    return out.astype(dtype), gate_logits


def load_balancing_loss(router_logits: jnp.ndarray, num_selected: int) -> jnp.ndarray:
    """Switch-style aux loss: E * sum_e (fraction_tokens_e * mean_prob_e)."""
    num_experts = router_logits.shape[-1]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    _, indices = jax.lax.top_k(router_logits, num_selected)
    counts = jax.nn.one_hot(indices, num_experts).sum(axis=(-3, -2))
    fraction = counts / jnp.maximum(counts.sum(), 1.0)
    mean_prob = probs.mean(axis=tuple(range(probs.ndim - 1)))
    return num_experts * jnp.sum(fraction * mean_prob)
