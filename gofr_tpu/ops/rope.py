"""Rotary position embeddings (RoPE), Llama-3 style.

Supports plain RoPE and Llama-3's frequency scaling for long context.
Computed in float32; applied as interleaved-free "rotate half" over the
head dimension (the GPT-NeoX convention Llama uses).
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 500000.0,
                     scaling: dict | None = None) -> jnp.ndarray:
    """Inverse frequencies [head_dim // 2], optionally Llama-3 scaled.

    ``scaling`` (Llama-3.1 long-context): {"factor": 8, "low_freq_factor": 1,
    "high_freq_factor": 4, "original_max_position": 8192}.
    """
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if scaling:
        factor = float(scaling.get("factor", 8.0))
        low = float(scaling.get("low_freq_factor", 1.0))
        high = float(scaling.get("high_freq_factor", 4.0))
        orig = float(scaling.get("original_max_position", 8192))
        wavelen = 2.0 * jnp.pi / inv
        # high-frequency (short wavelength) components keep full rotation;
        # low-frequency components are slowed by `factor`; in between,
        # smooth interpolation (Llama-3.1 recipe).
        smooth = jnp.clip((orig / wavelen - low) / (high - low), 0.0, 1.0)
        inv = jnp.where(wavelen < orig / high, inv,
                        jnp.where(wavelen > orig / low, inv / factor,
                                  (1 - smooth) * inv / factor + smooth * inv))
    return inv


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray,
               inv_freq: jnp.ndarray) -> jnp.ndarray:
    """Rotate ``x`` [..., seq, heads, head_dim] by position.

    ``positions`` is [..., seq] (absolute token positions, so paged /
    continued decode just passes the running offset).
    """
    # explicit lift of inv_freq [D/2] to positions' rank + 1: the test
    # harness runs jax_numpy_rank_promotion='raise'
    pos = positions[..., :, None].astype(jnp.float32)
    angles = pos * inv_freq.reshape((1,) * (pos.ndim - 1) + (-1,))  # [..., S, D/2]
    cos = jnp.cos(angles)[..., :, None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
