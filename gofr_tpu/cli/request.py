"""CLI argv as a Request: flags become params, positionals route.

Mirrors reference pkg/gofr/cmd/request.go (arg binder) and
cmd.go:64-89 (parsing): ``-k=v``, ``--k=v``, and bare ``-flag``
(true), with everything before the first flag treated as the
subcommand path. Values require ``=`` — ``--flag value`` is a bare
flag plus a stray arg, exactly as in the reference, which keeps
``tool deploy --verbose prod`` unambiguous.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

from ..http.request import bind_dataclass


def parse_args(argv: list[str]) -> tuple[list[str], dict[str, list[str]]]:
    """argv (no program name) -> (positional path, flag multimap)."""
    positionals: list[str] = []
    flags: dict[str, list[str]] = {}
    i = 0
    seen_flag = False
    while i < len(argv):
        arg = argv[i]
        if arg.startswith("-") and arg not in ("-", "--"):
            seen_flag = True
            name = arg.lstrip("-")
            if "=" in name:
                name, _, value = name.partition("=")
                flags.setdefault(name, []).append(value)
            else:
                flags.setdefault(name, []).append("true")
        elif not seen_flag:
            positionals.append(arg)
        else:
            flags.setdefault("_args", []).append(arg)
        i += 1
    return positionals, flags


class CMDRequest:
    """Request implementation over parsed argv."""

    def __init__(self, argv: list[str]) -> None:
        self.argv = list(argv)
        self.positionals, self.flags = parse_args(argv)
        self.subcommand = " ".join(self.positionals)

    def param(self, key: str) -> str:
        values = self.flags.get(key)
        return values[0] if values else ""

    def params(self, key: str) -> list[str]:
        out: list[str] = []
        for v in self.flags.get(key, []):
            out.extend(p for p in v.split(",") if p != "")
        return out

    def path_param(self, key: str) -> str:
        return self.param(key)

    def host_name(self) -> str:
        import socket
        return socket.gethostname()

    def header(self, key: str) -> str:
        return ""

    def bind(self, target: Any = None) -> Any:
        """Flags -> dict or dataclass (the reflection binder analog).
        Hyphenated flag names map to underscore field names
        (``--dry-run`` binds ``dry_run``)."""
        data: dict[str, Any] = {k.replace("-", "_"): v[0] if len(v) == 1 else v
                                for k, v in self.flags.items()}
        if target is None:
            return data
        if dataclasses.is_dataclass(target) and isinstance(target, type):
            return bind_dataclass(data, target)
        return data
