"""CLI application: subcommand routing on the same Handler model.

Mirrors reference pkg/gofr/cmd.go + factory.go:81 (NewCMD): parse argv,
prefix-match a registered subcommand route (cmd.go:121-134), build a
Context whose Request is the argv and whose terminal is attached, run
the handler, print the result (cmd/responder.go). Includes the help
system (cmd.go:137-200): ``help`` / ``-h`` / unknown command lists
every subcommand with its description and usage.
"""

from __future__ import annotations

import asyncio
import json
import sys
from dataclasses import dataclass, field
from typing import Any, Callable

from ..config.env import EnvConfig
from ..container.container import Container
from ..context import Context
from .request import CMDRequest
from .terminal import Out


@dataclass
class SubCommand:
    pattern: str
    handler: Callable
    description: str = ""
    help_text: str = ""
    segments: list[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        self.segments = self.pattern.split()


class CMDResponder:
    """Result -> stdout, error -> stderr (reference cmd/responder.go)."""

    def __init__(self, out: Out, err_out: Out) -> None:
        self.out = out
        self.err = err_out

    def respond(self, result: Any, error: Exception | None) -> int:
        if error is not None:
            self.err.print(self.err.red(f"error: {error}"))
            return 1
        if result is None:
            return 0
        if isinstance(result, str):
            self.out.print(result)
        elif isinstance(result, (bytes, bytearray)):
            self.out.stream.write(result.decode("utf-8", "replace"))
        else:
            self.out.print(json.dumps(result, indent=2, default=str))
        return 0


class CMDApp:
    """``new_cmd()`` application (reference factory.go:81): no servers,
    same Context/handler surface, argv in place of HTTP."""

    def __init__(self, config_dir: str = "configs", config=None) -> None:
        self.config = config if config is not None else EnvConfig(config_dir)
        self.container = Container.create(self.config)
        self.logger = self.container.logger
        self._subcommands: list[SubCommand] = []
        self.out = Out()
        self.err_out = Out(stream=sys.stderr)

    # ------------------------------------------------------ registration
    def sub_command(self, pattern: str, handler: Callable | None = None, *,
                    description: str = "", help: str = ""):
        """Register (decorator or direct) a subcommand
        (reference gofr.go:228 SubCommand)."""
        if handler is None:
            def decorator(fn: Callable) -> Callable:
                self.sub_command(pattern, fn, description=description,
                                 help=help)
                return fn
            return decorator
        self._subcommands.append(SubCommand(
            pattern=pattern, handler=handler, description=description,
            help_text=help))
        return handler

    # ------------------------------------------------------------ routing
    def _match(self, positionals: list[str]) -> SubCommand | None:
        """Longest-prefix match over registered patterns
        (reference cmd.go:121-134)."""
        best: SubCommand | None = None
        for sub in self._subcommands:
            n = len(sub.segments)
            if positionals[:n] == sub.segments:
                if best is None or n > len(best.segments):
                    best = sub
        return best

    def _print_help(self) -> None:
        name = self.container.app_name
        self.out.print(self.out.bold(f"{name} — available commands:"))
        width = max((len(s.pattern) for s in self._subcommands), default=0)
        for sub in sorted(self._subcommands, key=lambda s: s.pattern):
            line = f"  {sub.pattern:<{width}}  {sub.description}"
            self.out.print(line.rstrip())
            if sub.help_text:
                self.out.print(f"  {'':<{width}}  {sub.help_text}")
        self.out.print("  help" + " " * max(width - 4, 0) +
                       "  show this message")

    # ---------------------------------------------------------- execution
    def run(self, argv: list[str] | None = None) -> int:
        """Parse argv and execute; returns the process exit code
        (reference cmd.Run, cmd.go:37-61)."""
        argv = list(sys.argv[1:]) if argv is None else list(argv)
        request = CMDRequest(argv)

        wants_help = (request.subcommand in ("help", "") or
                      request.param("h") == "true" or
                      request.param("help") == "true")
        sub = self._match(request.positionals)
        if wants_help or sub is None:
            # -h/--help always shows help, matched subcommand or not
            self._print_help()
            return 0 if wants_help else 2

        responder = CMDResponder(self.out, self.err_out)
        ctx = Context(request=request, container=self.container,
                      responder=responder, terminal=self.out)
        try:
            result = sub.handler(ctx)
            if hasattr(result, "__await__"):
                async def _drain_then_run(coro):
                    # async-connect stores (NATS/MQTT pubsub) defer until
                    # a loop exists; CLI apps get one per async handler
                    await self.container.connect_async()
                    return await coro
                result = asyncio.run(_drain_then_run(result))
            return responder.respond(result, None)
        except Exception as exc:
            self.logger.debug(f"subcommand {sub.pattern!r} failed: {exc!r}")
            return responder.respond(None, exc)
