"""CLI runtime: subcommand apps + terminal TUI toolkit."""

from .cmd import CMDApp
from .request import CMDRequest, parse_args
from .terminal import Out, ProgressBar, Spinner

__all__ = ["CMDApp", "CMDRequest", "parse_args", "Out", "Spinner",
           "ProgressBar"]
