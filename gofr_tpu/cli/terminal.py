"""Terminal output: ANSI colors, cursor control, spinner, progress bar.

Mirrors reference pkg/gofr/cmd/terminal/ (output.go:126-256): a small
TUI toolkit for CLI apps — colored writes, line/screen clearing, an
animated spinner, and a progress bar, all degrading to plain text when
the stream is not a TTY.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import TextIO

RESET = "\x1b[0m"

_COLORS = {"black": 30, "red": 31, "green": 32, "yellow": 33, "blue": 34,
           "magenta": 35, "cyan": 36, "white": 37}

_SPINNER_FRAMES = "⠋⠙⠹⠸⠼⠴⠦⠧⠇⠏"


class Out:
    """The ``ctx.terminal`` object CLI handlers draw with."""

    def __init__(self, stream: TextIO | None = None,
                 force_tty: bool | None = None) -> None:
        self.stream = stream if stream is not None else sys.stdout
        if force_tty is not None:
            self.is_tty = force_tty
        else:
            self.is_tty = bool(getattr(self.stream, "isatty", lambda: False)())

    # ------------------------------------------------------------ writes
    def write(self, text: str) -> None:
        # `tool help | head` closes the pipe mid-output; exit quietly
        # like every well-behaved CLI instead of tracebacking
        try:
            self.stream.write(text)
        except BrokenPipeError:
            raise SystemExit(0)

    def print(self, *values: object, sep: str = " ", end: str = "\n") -> None:
        self.write(sep.join(str(v) for v in values) + end)

    def println(self, *values: object) -> None:
        self.print(*values)

    def printf(self, fmt: str, *args: object) -> None:
        self.write(fmt % args if args else fmt)

    def _colored(self, text: str, code: int) -> str:
        if not self.is_tty:
            return text
        return f"\x1b[{code}m{text}{RESET}"

    def color(self, text: str, name: str) -> str:
        return self._colored(text, _COLORS.get(name.lower(), 37))

    def bold(self, text: str) -> str:
        return self._colored(text, 1)

    # convenience like the reference's per-color helpers
    def green(self, text: str) -> str:
        return self.color(text, "green")

    def red(self, text: str) -> str:
        return self.color(text, "red")

    def yellow(self, text: str) -> str:
        return self.color(text, "yellow")

    def cyan(self, text: str) -> str:
        return self.color(text, "cyan")

    # ---------------------------------------------------- cursor control
    def clear_line(self) -> None:
        if self.is_tty:
            self.stream.write("\r\x1b[2K")

    def clear_screen(self) -> None:
        if self.is_tty:
            self.stream.write("\x1b[2J\x1b[H")

    def move_cursor_up(self, n: int = 1) -> None:
        if self.is_tty:
            self.stream.write(f"\x1b[{n}A")

    def hide_cursor(self) -> None:
        if self.is_tty:
            self.stream.write("\x1b[?25l")

    def show_cursor(self) -> None:
        if self.is_tty:
            self.stream.write("\x1b[?25h")

    # ----------------------------------------------------------- widgets
    def spinner(self, message: str = "") -> "Spinner":
        return Spinner(self, message)

    def progress_bar(self, total: int, width: int = 40) -> "ProgressBar":
        return ProgressBar(self, total, width)


class Spinner:
    """Animated while a with-block runs; single line on non-TTYs."""

    def __init__(self, out: Out, message: str = "",
                 interval: float = 0.08) -> None:
        self.out = out
        self.message = message
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def __enter__(self) -> "Spinner":
        if self.out.is_tty:
            self.out.hide_cursor()
            self._thread = threading.Thread(target=self._spin, daemon=True)
            self._thread.start()
        else:
            self.out.print(f"{self.message}...")
        return self

    def _spin(self) -> None:
        i = 0
        while not self._stop.is_set():
            frame = _SPINNER_FRAMES[i % len(_SPINNER_FRAMES)]
            self.out.write(f"\r{frame} {self.message}")
            self.out.stream.flush()
            i += 1
            time.sleep(self.interval)

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(1)
        if self.out.is_tty:
            self.out.clear_line()
            self.out.show_cursor()


class ProgressBar:
    """``[████----] 42%`` on TTYs; milestone lines otherwise."""

    def __init__(self, out: Out, total: int, width: int = 40) -> None:
        self.out = out
        self.total = max(total, 1)
        self.width = width
        self.current = 0
        self._last_printed_pct = -10

    def increment(self, n: int = 1) -> None:
        self.set(self.current + n)

    def set(self, value: int) -> None:
        self.current = min(value, self.total)
        pct = 100 * self.current // self.total
        if self.out.is_tty:
            filled = self.width * self.current // self.total
            bar = "█" * filled + "-" * (self.width - filled)
            self.out.write(f"\r[{bar}] {pct:3d}%")
            if self.current >= self.total:
                self.out.write("\n")
            self.out.stream.flush()
        elif pct >= self._last_printed_pct + 10 or self.current >= self.total:
            self._last_printed_pct = pct
            self.out.print(f"progress: {pct}%")
