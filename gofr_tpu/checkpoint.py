"""Checkpoint/resume: versioned param-tree persistence with
sharding-aware restore.

SURVEY §5: the reference's only resume state is the migration ledger;
the TPU build must add model/weights checkpointing — "loading compiled
executables + weights from disk/GCS at startup via OnStart hooks".

Format: one directory per step (``step_<n>/``) holding an ``.npz`` of
flattened leaves plus a JSON manifest (paths, dtypes, shapes). Writes
go to a temp dir then atomically rename, so a crash mid-save never
corrupts the latest checkpoint; a ``keep`` budget garbage-collects old
steps. Restore can place each leaf directly onto a
``jax.sharding.NamedSharding`` (mesh restore for the multi-chip path)
via ``sharding_fn`` — leaves go host->device once, already sharded.

Works for raw param pytrees and the train states of parallel/train.py
(any pytree of arrays + scalars).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import time
from pathlib import Path
from typing import Any, Callable

import numpy as np

_STEP_RE = re.compile(r"^step_(\d+)$")
MANIFEST = "manifest.json"
ARRAYS = "arrays.npz"


class CheckpointError(Exception):
    pass


def _flatten(pytree: Any) -> list[tuple[str, Any]]:
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(pytree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


class Checkpointer:
    def __init__(self, directory: str | Path, keep: int = 3,
                 logger: Any = None) -> None:
        self.directory = Path(directory)
        self.keep = keep
        self.logger = logger
        self.directory.mkdir(parents=True, exist_ok=True)

    # ------------------------------------------------------------- save
    def save(self, step: int, pytree: Any,
             metadata: dict | None = None) -> Path:
        import jax
        target = self.directory / f"step_{step}"
        if target.exists():
            raise CheckpointError(f"step {step} already saved")
        leaves = _flatten(pytree)
        arrays: dict[str, np.ndarray] = {}
        manifest: dict[str, Any] = {
            "step": step,
            "saved_at": time.time(),
            "metadata": metadata or {},
            "treedef": None,
            "leaves": [],
        }
        _, treedef = jax.tree_util.tree_flatten(pytree)
        manifest["treedef"] = str(treedef)
        for i, (key, leaf) in enumerate(leaves):
            name = f"leaf_{i}"
            array = np.asarray(leaf)
            # bf16 has no numpy dtype string round-trip; store raw bits
            if array.dtype.name == "bfloat16":
                arrays[name] = array.view(np.uint16)
                dtype = "bfloat16"
            else:
                arrays[name] = array
                dtype = array.dtype.name
            manifest["leaves"].append(
                {"key": key, "name": name, "dtype": dtype,
                 "shape": list(array.shape)})

        tmp = Path(tempfile.mkdtemp(dir=self.directory, prefix=".tmp_save_"))
        try:
            with open(tmp / ARRAYS, "wb") as f:
                np.savez(f, **arrays)
            (tmp / MANIFEST).write_text(json.dumps(manifest))
            os.replace(tmp, target)  # atomic publish
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if self.logger is not None:
            self.logger.info(f"checkpoint saved step={step}",
                             path=str(target))
        self._gc()
        return target

    def _gc(self) -> None:
        steps = self.steps()
        for old in steps[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(self.directory / f"step_{old}",
                          ignore_errors=True)

    # ------------------------------------------------------------ lookup
    def steps(self) -> list[int]:
        out = []
        for entry in self.directory.iterdir():
            match = _STEP_RE.match(entry.name)
            if match and (entry / MANIFEST).is_file():
                out.append(int(match.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    # ----------------------------------------------------------- restore
    def restore(self, step: int | None = None, *, like: Any = None,
                sharding_fn: Callable[[str], Any] | None = None) -> Any:
        """Load a checkpoint.

        ``like``: a pytree with the same structure (e.g. a freshly
        init'd param tree, or ``jax.eval_shape`` output) — restored
        leaves are rebuilt into its treedef. Without it, a dict keyed
        by flattened path strings is returned.
        ``sharding_fn(key) -> Sharding|None``: per-leaf placement; the
        leaf is device_put straight onto it (mesh-sharded restore).
        """
        import jax
        if step is None:
            step = self.latest_step()
        if step is None:
            raise CheckpointError(f"no checkpoints in {self.directory}")
        target = self.directory / f"step_{step}"
        if not (target / MANIFEST).is_file():
            raise CheckpointError(f"missing checkpoint step {step}")
        manifest = json.loads((target / MANIFEST).read_text())
        data = np.load(target / ARRAYS)

        leaves: list[Any] = []
        keys: list[str] = []
        import jax.numpy as jnp
        for entry in manifest["leaves"]:
            array = data[entry["name"]]
            if entry["dtype"] == "bfloat16":
                array = array.view(jnp.bfloat16)
            value: Any = array
            sharding = sharding_fn(entry["key"]) if sharding_fn else None
            if sharding is not None:
                value = jax.device_put(array, sharding)
            keys.append(entry["key"])
            leaves.append(value)

        if like is None:
            return dict(zip(keys, leaves))
        flat_like, treedef = jax.tree_util.tree_flatten(like)
        if len(flat_like) != len(leaves):
            raise CheckpointError(
                f"structure mismatch: checkpoint has {len(leaves)} leaves, "
                f"target has {len(flat_like)}")
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def restore_metadata(self, step: int | None = None) -> dict:
        if step is None:
            step = self.latest_step()
        if step is None:
            raise CheckpointError(f"no checkpoints in {self.directory}")
        manifest = json.loads(
            (self.directory / f"step_{step}" / MANIFEST).read_text())
        return manifest.get("metadata", {})


def warm_start(app: Any, name: str, directory: str | Path,
               build_engine: Callable[[Any], Any]) -> None:
    """OnStart-hook wiring (SURVEY §5): restore the latest checkpoint
    and serve the engine it builds, before the server accepts traffic.

    ``build_engine(params) -> engine`` gets the restored tree.
    """
    checkpointer = Checkpointer(directory, logger=app.logger)

    @app.on_start
    def _load(container):
        step = checkpointer.latest_step()
        if step is None:
            raise CheckpointError(
                f"warm start of {name!r}: no checkpoint in {directory}")
        params = checkpointer.restore(step)
        engine = build_engine(params)
        app.serve_model(name, engine)
        engine.start()
        app.logger.info(f"warm-started {name} from step {step}")
