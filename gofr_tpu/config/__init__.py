from .env import Config, DictConfig, EnvConfig, load_env_file

__all__ = ["Config", "DictConfig", "EnvConfig", "load_env_file"]
