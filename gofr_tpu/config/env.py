"""Environment-file configuration with GoFr's precedence semantics.

The reference loads ``./configs/.env`` then overlays
``./configs/.{APP_ENV}.env``, with real OS environment variables always
winning (reference: pkg/gofr/config/godotenv.go:29-77, config/config.go:3-6).
This module reimplements that contract for the TPU build: a ``Config``
protocol with ``get``/``get_or_default`` and an ``EnvConfig`` that reads
env files into a layered map.

No third-party dotenv dependency: the parser handles comments, blank
lines, ``export`` prefixes, single/double quotes, and ``KEY=VALUE`` pairs.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Mapping, Protocol


class Config(Protocol):
    """Read-only config surface handed to every subsystem.

    Mirrors the two-method interface at reference config/config.go:3-6.
    """

    def get(self, key: str) -> str | None: ...

    def get_or_default(self, key: str, default: str) -> str: ...


def _parse_env_line(line: str) -> tuple[str, str] | None:
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    if line.startswith("export "):
        line = line[len("export "):].lstrip()
    if "=" not in line:
        return None
    key, _, value = line.partition("=")
    key = key.strip()
    if not key:
        return None
    value = value.strip()
    # Strip one layer of matching quotes; keep inline `#` inside quotes.
    if len(value) >= 2 and value[0] == value[-1] and value[0] in ("'", '"'):
        value = value[1:-1]
    else:
        # Unquoted values lose trailing comments.
        hash_idx = value.find(" #")
        if hash_idx != -1:
            value = value[:hash_idx].rstrip()
    return key, value


def load_env_file(path: str | Path) -> dict[str, str]:
    """Parse a dotenv file into a dict. Missing file -> empty dict."""
    out: dict[str, str] = {}
    p = Path(path)
    if not p.is_file():
        return out
    for line in p.read_text().splitlines():
        kv = _parse_env_line(line)
        if kv is not None:
            out[kv[0]] = kv[1]
    return out


class EnvConfig:
    """Layered env config: ``.env`` -> ``.{APP_ENV}.env`` -> OS env (wins).

    ``configs_dir`` defaults to ``./configs`` like the reference
    (pkg/gofr/gofr.go:187 readConfig).
    """

    def __init__(self, configs_dir: str | Path = "configs",
                 environ: Mapping[str, str] | None = None) -> None:
        self._environ: Mapping[str, str] = environ if environ is not None else os.environ
        base = Path(configs_dir)
        layered: dict[str, str] = {}
        layered.update(load_env_file(base / ".env"))
        app_env = self._environ.get("APP_ENV") or layered.get("APP_ENV")
        if app_env:
            layered.update(load_env_file(base / f".{app_env}.env"))
        self._file_values = layered

    def get(self, key: str) -> str | None:
        if key in self._environ:
            return self._environ[key]
        return self._file_values.get(key)

    def get_or_default(self, key: str, default: str) -> str:
        value = self.get(key)
        return value if value not in (None, "") else default

    def get_int(self, key: str, default: int) -> int:
        try:
            return int(self.get_or_default(key, str(default)))
        except ValueError:
            return default

    def get_float(self, key: str, default: float) -> float:
        try:
            return float(self.get_or_default(key, str(default)))
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        value = self.get(key)
        if value is None or value == "":
            return default
        return value.strip().lower() in ("1", "true", "yes", "on")


# --------------------------------------------------- XLA compile cache
#
# The ONE shared config path for the persistent XLA compilation cache.
# Everything that compiles serving graphs — the engine, bench children,
# every scripts/tpu_jobs/*.py entry point — resolves the directory
# here, so warmup compiles amortize across processes instead of being
# re-paid per child (round 5 burned its ~35-minute TPU window ~10:1 on
# recompiles because nothing in the tree set jax_compilation_cache_dir).

#: env / config key; value "off"/"none"/"0"/"false" disables, empty or
#: unset falls back to the default directory below
COMPILE_CACHE_ENV = "GOFR_COMPILE_CACHE_DIR"

_OFF_VALUES = ("off", "none", "0", "false", "disabled")


def default_compile_cache_dir() -> str:
    """``$XDG_CACHE_HOME/gofr_tpu/xla_cache`` (``~/.cache`` fallback)
    — stable across processes and repo checkouts, so bench children,
    TPU jobs and restarted servers all hit the same cache."""
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(root, "gofr_tpu", "xla_cache")


def resolve_compile_cache_dir(config: "Config | None" = None) -> str | None:
    """Resolve the cache directory from the shared config key
    (``Config`` layer if given, else the OS environment), falling back
    to :func:`default_compile_cache_dir`. ``None`` = disabled."""
    value = config.get(COMPILE_CACHE_ENV) if config is not None else None
    if value is None:
        value = os.environ.get(COMPILE_CACHE_ENV)
    if value is None or value == "":
        return default_compile_cache_dir()
    if value.strip().lower() in _OFF_VALUES:
        return None
    return value


#: directory this process last enabled — guards the reset below
_enabled_dir: str | None = None


def enable_compile_cache(dir_or_auto: str | None = "auto") -> str | None:
    """Point JAX's persistent compilation cache at the shared
    directory. "auto" resolves via :func:`resolve_compile_cache_dir`;
    an explicit path is used as-is; ``None``/"off" disables (no-op).
    Thresholds are lowered so every executable caches — the serving
    graphs are many small jits (per-bucket prefills, decode windows)
    whose compile time is individually under JAX's 1 s default floor
    but collectively the whole warmup wall. Idempotent; returns the
    directory actually enabled (or None). Best-effort: an unwritable
    directory or an old JAX just leaves the cache off."""
    global _enabled_dir
    if dir_or_auto is None:
        return None
    if dir_or_auto == "auto":
        path = resolve_compile_cache_dir()
    elif str(dir_or_auto).strip().lower() in _OFF_VALUES:
        path = None
    else:
        path = str(dir_or_auto)
    if path is None:
        return None
    if path == _enabled_dir:
        return path
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        return None
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # JAX binds the persistent cache ONCE, at the first compile: a
        # process that compiled anything before this call (model init,
        # another engine) silently keeps the cache OFF unless the
        # handle is reset to re-read the directory
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:  # pragma: no cover — older jax without the knobs
        return None
    _enabled_dir = path
    return path


class DictConfig:
    """In-memory config for tests and embedding (no files, no OS env)."""

    def __init__(self, values: Mapping[str, str] | None = None) -> None:
        self._values = dict(values or {})

    def get(self, key: str) -> str | None:
        return self._values.get(key)

    def get_or_default(self, key: str, default: str) -> str:
        value = self._values.get(key)
        return value if value not in (None, "") else default

    def get_int(self, key: str, default: int) -> int:
        try:
            return int(self.get_or_default(key, str(default)))
        except ValueError:
            return default

    def get_float(self, key: str, default: float) -> float:
        try:
            return float(self.get_or_default(key, str(default)))
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        value = self._values.get(key)
        if value is None or value == "":
            return default
        return value.strip().lower() in ("1", "true", "yes", "on")

    def set(self, key: str, value: str) -> None:
        self._values[key] = value
