from .client import (
    APIKeyAuth,
    BasicAuth,
    CircuitBreaker,
    CircuitOpenError,
    CustomHeaders,
    HealthConfig,
    HTTPService,
    OAuth2ClientCredentials,
    RateLimit,
    RateLimitedError,
    Response,
    Retry,
    ServiceError,
    new_http_service,
    probe_leader,
    resolve_leader,
)

__all__ = [
    "APIKeyAuth", "BasicAuth", "CircuitBreaker", "CircuitOpenError",
    "CustomHeaders", "HealthConfig", "HTTPService",
    "OAuth2ClientCredentials", "RateLimit", "RateLimitedError", "Response",
    "Retry", "ServiceError", "new_http_service", "probe_leader",
    "resolve_leader",
]
