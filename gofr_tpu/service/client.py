"""Resilient inter-service HTTP client with decorator options.

Mirrors reference pkg/gofr/service/: ``new_http_service(url, *options)``
builds a client whose options wrap the base transport
(service/new.go:68-88, options.go:3-5): circuit breaker with background
half-open probing (circuit_breaker.go:24-128), bounded retry
(retry.go:8-95), token-bucket rate limiting (rate_limiter.go:17-39),
basic / API-key / OAuth2 client-credentials auth, custom headers, and a
configurable health check. Every request propagates the active trace
(traceparent header) and records the ``app_http_service_response``
histogram + a structured log with the correlation id.

Transport: asyncio streams (same parser family as the server side) —
async-native so handlers awaiting downstream calls never block the
serving loop.
"""

from __future__ import annotations

import asyncio
import base64
import json as json_mod
import ssl as ssl_mod
import time
from dataclasses import dataclass, field
from typing import Any, Mapping
from urllib.parse import urlencode, urlsplit

from ..http.server import MAX_HEADER_BYTES


@dataclass
class Response:
    status: int
    headers: dict[str, str]
    body: bytes

    def json(self) -> Any:
        return json_mod.loads(self.body) if self.body else None

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300


class ServiceError(Exception):
    pass


class CircuitOpenError(ServiceError):
    def __init__(self, url: str) -> None:
        super().__init__(f"circuit breaker open for {url}")


class RateLimitedError(ServiceError):
    def __init__(self, url: str) -> None:
        super().__init__(f"client-side rate limit exceeded for {url}")


async def _raw_request(method: str, url: str, *, headers: Mapping[str, str],
                       body: bytes, timeout: float) -> Response:
    split = urlsplit(url)
    host = split.hostname or "localhost"
    use_tls = split.scheme == "https"
    port = split.port or (443 if use_tls else 80)
    path = split.path or "/"
    if split.query:
        path += "?" + split.query

    ssl_ctx = ssl_mod.create_default_context() if use_tls else None
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port, ssl=ssl_ctx,
                                limit=MAX_HEADER_BYTES),
        timeout)
    try:
        head_lines = [f"{method} {path} HTTP/1.1",
                      f"Host: {split.netloc}",
                      "Connection: close",
                      f"Content-Length: {len(body)}"]
        head_lines.extend(f"{k}: {v}" for k, v in headers.items())
        writer.write(("\r\n".join(head_lines) + "\r\n\r\n").encode("latin-1")
                     + body)
        await writer.drain()

        raw = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), timeout)
        lines = raw.decode("latin-1").split("\r\n")
        parts = lines[0].split(" ", 2)
        status = int(parts[1])
        resp_headers: dict[str, str] = {}
        for line in lines[1:]:
            if ":" in line:
                k, _, v = line.partition(":")
                resp_headers[k.strip().lower()] = v.strip()

        if resp_headers.get("transfer-encoding", "").lower() == "chunked":
            chunks = []
            while True:
                size_line = await asyncio.wait_for(reader.readline(), timeout)
                size = int(size_line.strip().split(b";")[0] or b"0", 16)
                if size == 0:
                    break
                chunks.append(await asyncio.wait_for(
                    reader.readexactly(size), timeout))
                await reader.readexactly(2)
            resp_body = b"".join(chunks)
        elif "content-length" in resp_headers:
            resp_body = await asyncio.wait_for(
                reader.readexactly(int(resp_headers["content-length"])),
                timeout)
        else:
            resp_body = await asyncio.wait_for(reader.read(), timeout)
        return Response(status=status, headers=resp_headers, body=resp_body)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError):
            pass


# ------------------------------------------------------------- options

class Option:
    """Decorators around the request call; subclasses override hooks."""

    def bind(self, service: "HTTPService") -> None:
        self.service = service

    async def before(self, headers: dict[str, str]) -> None:
        pass

    async def around(self, call, method, path, headers, body):
        return await call(method, path, headers, body)


@dataclass
class BasicAuth(Option):
    username: str
    password: str

    async def before(self, headers: dict[str, str]) -> None:
        token = base64.b64encode(
            f"{self.username}:{self.password}".encode()).decode()
        headers["Authorization"] = f"Basic {token}"


@dataclass
class APIKeyAuth(Option):
    api_key: str
    header: str = "X-Api-Key"

    async def before(self, headers: dict[str, str]) -> None:
        headers[self.header] = self.api_key


@dataclass
class CustomHeaders(Option):
    headers: dict[str, str] = field(default_factory=dict)

    async def before(self, headers: dict[str, str]) -> None:
        headers.update(self.headers)


@dataclass
class OAuth2ClientCredentials(Option):
    token_url: str
    client_id: str
    client_secret: str
    scopes: str = ""
    _token: str | None = None
    _expiry: float = 0.0

    async def before(self, headers: dict[str, str]) -> None:
        if self._token is None or time.time() >= self._expiry - 30:
            form = {"grant_type": "client_credentials",
                    "client_id": self.client_id,
                    "client_secret": self.client_secret}
            if self.scopes:
                form["scope"] = self.scopes
            resp = await _raw_request(
                "POST", self.token_url,
                headers={"Content-Type": "application/x-www-form-urlencoded"},
                body=urlencode(form).encode(), timeout=10.0)
            if not resp.ok:
                raise ServiceError(
                    f"oauth token fetch failed: {resp.status}")
            payload = resp.json() or {}
            self._token = payload.get("access_token", "")
            self._expiry = time.time() + float(payload.get("expires_in", 300))
        headers["Authorization"] = f"Bearer {self._token}"


def parse_retry_after(value: str) -> float | None:
    """RFC 9110 ``Retry-After``: delta-seconds or an HTTP-date.
    Returns the wait in seconds (floored at 0), or None when the
    header is absent/unparseable — callers fall back to backoff."""
    if not value:
        return None
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    from email.utils import parsedate_to_datetime
    try:
        when = parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if when is None:
        return None
    return max(0.0, when.timestamp() - time.time())


@dataclass
class Retry(Option):
    """Bounded retries with exponential backoff; 429/503 responses
    carrying ``Retry-After`` (GoFr-parity, SURVEY §7) wait what the
    server asked instead — capped by ``max_retry_after_s`` so a
    hostile/buggy upstream cannot park the client for an hour."""
    max_retries: int = 3
    backoff_s: float = 0.05
    #: honor Retry-After on 429/503 (429 is retried ONLY when the
    #: server sent the header — a plain 429 is the caller's quota
    #: problem, not a transient)
    honor_retry_after: bool = True
    max_retry_after_s: float = 30.0

    def _server_wait(self, resp) -> float | None:
        if not self.honor_retry_after or resp.status not in (429, 503):
            return None
        wait = parse_retry_after(resp.headers.get("retry-after", ""))
        if wait is None:
            return None
        return min(wait, self.max_retry_after_s)

    async def around(self, call, method, path, headers, body):
        last_exc: Exception | None = None
        for attempt in range(self.max_retries + 1):
            try:
                resp = await call(method, path, headers, body)
                if attempt < self.max_retries:
                    wait = self._server_wait(resp)
                    if wait is not None:
                        await asyncio.sleep(wait)
                        continue
                    if resp.status >= 500:
                        await asyncio.sleep(self.backoff_s * (2 ** attempt))
                        continue
                return resp
            except (OSError, asyncio.TimeoutError, asyncio.IncompleteReadError) as exc:
                last_exc = exc
                if attempt < self.max_retries:
                    await asyncio.sleep(self.backoff_s * (2 ** attempt))
        raise ServiceError(f"request failed after {self.max_retries + 1} "
                           f"attempts: {last_exc!r}")


@dataclass
class RateLimit(Option):
    """Token bucket: ``rate`` requests/second with ``burst`` capacity."""
    rate: float = 10.0
    burst: int = 10

    def __post_init__(self) -> None:
        self._tokens = float(self.burst)
        self._last = time.monotonic()

    async def around(self, call, method, path, headers, body):
        now = time.monotonic()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens < 1.0:
            raise RateLimitedError(self.service.base_url)
        self._tokens -= 1.0
        return await call(method, path, headers, body)


@dataclass
class CircuitBreaker(Option):
    """Opens after ``threshold`` consecutive failures.

    Recovery is two-pronged (reference circuit_breaker.go:24-128 uses a
    background prober): inside a long-lived event loop a background task
    probes the health endpoint every ``interval_s`` and closes on
    success; additionally — so short-lived loops (``asyncio.run`` per
    call) can never strand the circuit open — one trial request per
    ``interval_s`` is let through half-open, closing the circuit when it
    succeeds."""
    threshold: int = 5
    interval_s: float = 1.0

    def __post_init__(self) -> None:
        self._failures = 0
        self._open = False
        self._last_probe = 0.0
        self._probe_task: asyncio.Task | None = None

    @property
    def is_open(self) -> bool:
        return self._open

    async def around(self, call, method, path, headers, body):
        if self._open:
            now = time.monotonic()
            if now - self._last_probe < self.interval_s:
                raise CircuitOpenError(self.service.base_url)
            self._last_probe = now  # half-open: this request is the trial
        try:
            resp = await call(method, path, headers, body)
        except Exception:
            self._record_failure()
            raise
        if resp.status >= 500:
            self._record_failure()
        else:
            self._failures = 0
            self._open = False
        return resp

    def _record_failure(self) -> None:
        self._failures += 1
        if self._failures >= self.threshold and not self._open:
            self._open = True
            self._last_probe = time.monotonic()
            if self._probe_task is None or self._probe_task.done():
                try:
                    self._probe_task = asyncio.ensure_future(self._probe())
                except RuntimeError:
                    self._probe_task = None  # no loop: lazy half-open only

    async def _probe(self) -> None:
        while self._open:
            await asyncio.sleep(self.interval_s)
            try:
                resp = await self.service.health_check()
                if resp.get("status") == "UP":
                    self._open = False
                    self._failures = 0
            except Exception:
                continue


@dataclass
class HealthConfig(Option):
    path: str = "/.well-known/alive"
    timeout_s: float = 5.0


# -------------------------------------------------------------- service

class HTTPService:
    def __init__(self, base_url: str, *options: Option,
                 timeout: float = 30.0, logger: Any = None,
                 metrics: Any = None, tracer: Any = None,
                 service_name: str = "") -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        self.service_name = service_name or urlsplit(base_url).netloc
        self.options = list(options)
        self.health = next((o for o in self.options
                            if isinstance(o, HealthConfig)), HealthConfig())
        for opt in self.options:
            opt.bind(self)

    # -- core call with decorators applied
    async def request(self, method: str, path: str, *,
                      params: Mapping[str, Any] | None = None,
                      json: Any = None, body: bytes | None = None,
                      headers: Mapping[str, str] | None = None) -> Response:
        hdrs = {k: str(v) for k, v in (headers or {}).items()}
        if json is not None:
            body = json_mod.dumps(json).encode()
            hdrs.setdefault("Content-Type", "application/json")
        body = body or b""
        if params:
            path = path + ("&" if "?" in path else "?") + urlencode(params)
        if self.tracer is not None:
            self.tracer.inject_headers(hdrs)

        for opt in self.options:
            await opt.before(hdrs)

        async def base_call(method, path, headers, body):
            return await _raw_request(
                method, self.base_url + path, headers=headers, body=body,
                timeout=self.timeout)

        call = base_call
        for opt in reversed(self.options):
            call = self._wrap(opt, call)

        start = time.perf_counter()
        try:
            resp = await call(method, path, hdrs, body)
        finally:
            elapsed = time.perf_counter() - start
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_http_service_response", elapsed,
                    service=self.service_name, method=method)
        if self.logger is not None:
            self.logger.debug(
                f"{method} {self.service_name}{path} -> {resp.status} "
                f"({elapsed * 1000:.1f}ms)")
        return resp

    @staticmethod
    def _wrap(opt: Option, call):
        async def wrapped(method, path, headers, body):
            return await opt.around(call, method, path, headers, body)
        return wrapped

    # -- verb surface (reference new.go:26-64)
    async def get(self, path: str, **kw) -> Response:
        return await self.request("GET", path, **kw)

    async def post(self, path: str, **kw) -> Response:
        return await self.request("POST", path, **kw)

    async def put(self, path: str, **kw) -> Response:
        return await self.request("PUT", path, **kw)

    async def patch(self, path: str, **kw) -> Response:
        return await self.request("PATCH", path, **kw)

    async def delete(self, path: str, **kw) -> Response:
        return await self.request("DELETE", path, **kw)

    async def health_check(self) -> dict:
        try:
            resp = await _raw_request(
                "GET", self.base_url + self.health.path, headers={},
                body=b"", timeout=self.health.timeout_s)
            if resp.ok:
                return {"status": "UP"}
            return {"status": "DOWN", "code": resp.status}
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}


def new_http_service(base_url: str, *options: Option, **kw) -> HTTPService:
    return HTTPService(base_url, *options, **kw)


# --------------------------------------------------------------- leader
# discovery (docs/operations.md "Losing the leader"): sync, stdlib-only
# probes of GET /control/leader so external callers — CLIs, sidecars,
# the WorkerAgent's failover walk — can re-dial the active front door
# without DNS churn. Sync on purpose: the walk runs from heartbeat
# threads and shutdown hooks where spinning an event loop is overkill.

def probe_leader(url: str, *, timeout_s: float = 2.0) -> dict | None:
    """``GET {url}/control/leader`` and return the leadership doc
    (``active``, ``epoch``, ``rank``, ``host_id``, ``candidates``,
    ``converging``) or None when the candidate is unreachable or
    answers garbage. Never raises — an absent candidate is a normal
    input to the election, not an error."""
    import http.client
    import json as _json
    from urllib.parse import urlsplit
    parts = urlsplit(url if "//" in url else "http://" + url)
    host, port = parts.hostname or "", parts.port or 80
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", "/control/leader")
        resp = conn.getresponse()
        if resp.status != 200:
            return None
        doc = _json.loads(resp.read().decode("utf-8"))
        data = doc.get("data", doc)
        return data if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None
    finally:
        conn.close()


def resolve_leader(candidates, *, epoch_at_least: int = -1,
                   timeout_s: float = 2.0) -> dict | None:
    """Walk ranked ``candidates`` and return the ACTIVE leader as
    ``{"url", "rank", **leadership}`` — the highest epoch wins, ties
    break to the lowest rank, and an active candidate whose epoch is
    below ``epoch_at_least`` is a revived stale leader and is skipped
    (the same fencing rule the workers apply). None when no candidate
    is active."""
    best = None
    for rank, url in enumerate(candidates):
        info = probe_leader(url, timeout_s=timeout_s)
        if info is None or not info.get("active"):
            continue
        epoch = int(info.get("epoch", -1))
        if epoch < epoch_at_least:
            continue
        if best is None or epoch > best["epoch"]:
            best = dict(info, url=url, rank=rank, epoch=epoch)
    return best
