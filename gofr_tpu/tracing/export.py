"""Network span exporters: OTLP/HTTP JSON and Zipkin v2.

The reference wires OTel exporters to real collectors by URL —
otlp/zipkin/jaeger/gofr (reference pkg/gofr/otel.go:131-151). These are
the same egress paths for this tracer: spans batch in a background
thread (ending a span never blocks a request on network IO) and POST
as JSON to the collector; failures log and drop, never crash or block
the app.

- :class:`OTLPHTTPExporter` — OTLP/HTTP with the standard proto3-JSON
  encoding of ``ExportTraceServiceRequest``, POSTed to
  ``<endpoint>/v1/traces`` (any OTel collector accepts it).
- :class:`ZipkinExporter` — Zipkin v2 JSON to ``<endpoint>/api/v2/spans``
  (zipkin, jaeger's zipkin port, grafana tempo).

Selected by ``TRACE_EXPORTER=otlp|zipkin`` + ``TRACER_URL`` (container
wiring, reference otel.go's exporter switch).
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.request
from typing import Any

from .tracer import Span


class _BatchingHTTPExporter:
    """Shared batch/flush machinery: export() enqueues, a daemon thread
    drains into POSTs of up to ``batch_size`` spans."""

    def __init__(self, endpoint: str, path: str, *,
                 batch_size: int = 64, flush_interval_s: float = 2.0,
                 timeout_s: float = 5.0, logger: Any = None) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.path = path
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self.timeout_s = timeout_s
        self.logger = logger
        self.sent = 0
        self.dropped = 0
        self._queue: queue.Queue = queue.Queue(maxsize=4096)
        self._closed = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="gofr-trace-export")
        self._thread.start()

    def export(self, span: Span) -> None:
        try:
            self._queue.put_nowait(span)
        except queue.Full:
            self.dropped += 1  # backpressure: drop, never block a request

    def _loop(self) -> None:
        while not self._closed.is_set():
            batch = self._drain()
            if batch:
                self._post(batch)

    def _drain(self) -> list[Span]:
        batch: list[Span] = []
        try:
            batch.append(self._queue.get(timeout=self.flush_interval_s))
        except queue.Empty:
            return batch
        while len(batch) < self.batch_size:
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return batch

    def _post(self, batch: list[Span]) -> None:
        body = json.dumps(self.encode(batch)).encode()
        req = urllib.request.Request(
            self.endpoint + self.path, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            self.sent += len(batch)
        except Exception as exc:
            self.dropped += len(batch)
            if self.logger is not None:
                self.logger.warn(f"trace export failed: {exc}")

    def encode(self, batch: list[Span]) -> Any:  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        """Flush what's queued, then stop the worker."""
        batch = []
        try:
            while True:
                batch.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        if batch:
            self._post(batch)
        self._closed.set()
        self._thread.join(timeout=self.flush_interval_s + 1)


class OTLPHTTPExporter(_BatchingHTTPExporter):
    def __init__(self, endpoint: str, service_name: str = "gofr-app",
                 **kw: Any) -> None:
        super().__init__(endpoint, "/v1/traces", **kw)
        self.service_name = service_name

    def encode(self, batch: list[Span]) -> dict:
        spans = []
        for s in batch:
            end = s.end_time if s.end_time is not None else s.start_time
            spans.append({
                "traceId": s.trace_id,
                "spanId": s.span_id,
                **({"parentSpanId": s.parent_id} if s.parent_id else {}),
                "name": s.name,
                "kind": 2,  # SPAN_KIND_SERVER
                "startTimeUnixNano": str(int(s.start_time * 1e9)),
                "endTimeUnixNano": str(int(end * 1e9)),
                "attributes": [
                    {"key": k, "value": {"stringValue": str(v)}}
                    for k, v in s.attributes.items()],
                "status": {"code": 1 if s.status == "OK" else 2},
            })
        return {"resourceSpans": [{
            "resource": {"attributes": [{
                "key": "service.name",
                "value": {"stringValue": self.service_name}}]},
            "scopeSpans": [{"scope": {"name": "gofr_tpu"},
                            "spans": spans}],
        }]}


class ZipkinExporter(_BatchingHTTPExporter):
    def __init__(self, endpoint: str, service_name: str = "gofr-app",
                 **kw: Any) -> None:
        super().__init__(endpoint, "/api/v2/spans", **kw)
        self.service_name = service_name

    def encode(self, batch: list[Span]) -> list:
        out = []
        for s in batch:
            end = s.end_time if s.end_time is not None else s.start_time
            out.append({
                "traceId": s.trace_id,
                "id": s.span_id,
                **({"parentId": s.parent_id} if s.parent_id else {}),
                "name": s.name,
                "kind": "SERVER",
                "timestamp": int(s.start_time * 1e6),
                "duration": max(1, int((end - s.start_time) * 1e6)),
                "localEndpoint": {"serviceName": self.service_name},
                "tags": {k: str(v) for k, v in s.attributes.items()},
            })
        return out
